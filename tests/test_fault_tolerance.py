"""Fault tolerance: task ledger accounting, atomic checkpoints, shm
cleanup, and the full chaos end-to-end (learner + worker host over real TCP
with an injected gather kill and a severed data socket).

Hub-level liveness/heartbeat behavior is pinned in tests/test_hub.py.
"""

import json
import os
import signal
import subprocess
import sys
import time
from multiprocessing import shared_memory

import numpy as np
import pytest

from handyrl_tpu.fault import Backoff, TaskLedger, parse_chaos
from handyrl_tpu.utils.fs import atomic_write_bytes


# ---------------------------------------------------------------------------
# task ledger


class _Clock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_ledger_assign_complete_roundtrip():
    clock = _Clock()
    ledger = TaskLedger(deadline=30.0, clock=clock)
    task = {'role': 'g', 'model_id': {0: 1, 1: 1}, 'player': [0, 1]}
    tid = ledger.assign('ep-a', task)
    assert task['task_id'] == tid
    assert ledger.outstanding() == 1
    admitted = ledger.admit([{'args': {'task_id': tid}, 'outcome': {}}])
    assert len(admitted) == 1
    assert ledger.outstanding() == 0
    assert ledger.stats['completed'] == 1


def test_ledger_drops_duplicate_uploads():
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    tid = ledger.assign('ep-a', {'role': 'g', 'model_id': {}})
    first = ledger.admit([{'args': {'task_id': tid}}])
    dup = ledger.admit([{'args': {'task_id': tid}}])
    assert len(first) == 1 and len(dup) == 0
    assert ledger.stats['duplicates'] == 1
    # items with no task_id (pre-ledger peers) and Nones pass untouched
    passthrough = ledger.admit([None, {'args': {}}])
    assert len(passthrough) == 2


def test_ledger_reissues_on_endpoint_failure_without_recounting():
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    orig = {'role': 'g', 'model_id': {0: 5}, 'player': [0, 1]}
    ledger.assign('ep-dead', orig)
    ledger.assign('ep-live', {'role': 'e', 'model_id': {}})
    assert ledger.fail_endpoint('ep-dead') == 1
    assert ledger.pending_reissue() == 1
    again = ledger.next_reissue()
    # the re-issued payload is the original task, sans the stale task_id
    assert again['role'] == 'g' and again['model_id'] == {0: 5}
    assert 'task_id' not in again
    new_tid = ledger.assign('ep-live', again)
    assert new_tid != orig['task_id']
    assert ledger.outstanding() == 2
    assert ledger.fail_endpoint('ep-dead') == 0   # nothing left booked there


def test_ledger_deadline_reap():
    clock = _Clock()
    ledger = TaskLedger(deadline=10.0, clock=clock)
    ledger.assign('ep', {'role': 'g', 'model_id': {}})
    assert ledger.reap() == 0
    clock.now += 11.0
    assert ledger.reap() == 1
    assert ledger.outstanding() == 0
    assert ledger.pending_reissue() == 1
    assert ledger.stats['expired'] == 1
    # a straggler completing AFTER expiry is treated as a duplicate
    assert ledger.admit([{'args': {'task_id': 0}}]) == []


def test_backoff_is_bounded_and_jittered():
    backoff = Backoff(initial=1.0, maximum=8.0, jitter=0.5)
    delays = [backoff.next_delay() for _ in range(8)]
    assert all(0.5 <= d <= 8.0 for d in delays)
    assert delays[-1] > 2.0          # grew toward the ceiling
    backoff.reset()
    assert backoff.next_delay() <= 1.0


def test_parse_chaos():
    assert parse_chaos('') == {}
    assert parse_chaos('kill_gather=8,max_kills=2') == {
        'kill_gather': 8.0, 'max_kills': 2.0}
    assert parse_chaos('garbage') == {}   # malformed entries are ignored


# ---------------------------------------------------------------------------
# atomic checkpoint writes


def test_atomic_write_publishes_complete_bytes(tmp_path):
    target = tmp_path / 'latest.ckpt'
    atomic_write_bytes(str(target), b'v1')
    assert target.read_bytes() == b'v1'
    atomic_write_bytes(str(target), b'v2-longer')
    assert target.read_bytes() == b'v2-longer'
    assert os.listdir(tmp_path) == ['latest.ckpt']   # no temp litter


def test_interrupted_save_never_corrupts_target(tmp_path, monkeypatch):
    """A crash anywhere before the final rename leaves the old checkpoint
    bytes fully intact and no stray temp files."""
    target = tmp_path / 'latest.ckpt'
    target.write_bytes(b'GOOD-CHECKPOINT')

    # crash at the publish step (after the temp write)
    def boom(src, dst):
        raise OSError('simulated crash mid-save')
    monkeypatch.setattr(os, 'replace', boom)
    with pytest.raises(OSError):
        atomic_write_bytes(str(target), b'half-written-new-bytes')
    assert target.read_bytes() == b'GOOD-CHECKPOINT'
    assert os.listdir(tmp_path) == ['latest.ckpt']

    # crash during the temp write itself (e.g. ENOSPC / power loss window)
    monkeypatch.undo()

    class _ExplodingBytes(bytes):
        pass
    real_fdopen = os.fdopen

    def exploding_fdopen(fd, *a, **k):
        f = real_fdopen(fd, *a, **k)
        orig_write = f.write

        def write(data):
            orig_write(data[: len(data) // 2])
            raise OSError('simulated torn write')
        f.write = write
        return f
    monkeypatch.setattr(os, 'fdopen', exploding_fdopen)
    with pytest.raises(OSError):
        atomic_write_bytes(str(target), b'another-new-version')
    assert target.read_bytes() == b'GOOD-CHECKPOINT'
    assert os.listdir(tmp_path) == ['latest.ckpt']


# ---------------------------------------------------------------------------
# shared-memory arena cleanup


def test_arena_ring_close_is_idempotent_and_unlinks():
    from handyrl_tpu.ops.shm_batch import ArenaRing, batch_spec
    spec = batch_spec({'a': np.zeros((4, 4), np.float32)})
    ring = ArenaRing(spec, slots=2)
    names = list(ring.names)
    assert len(names) == 2
    ring.close()
    ring.close()   # double close/unlink must be a no-op, not an error
    for name in names:
        with pytest.raises(FileNotFoundError):
            shared_memory.SharedMemory(name=name)


# ---------------------------------------------------------------------------
# chaos end-to-end: gather kill + severed data socket over real TCP


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax, json
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 2,
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': %(model_dir)r,
                          'fault_tolerance': {
                              'heartbeat_interval': 1.0,
                              'liveness_timeout': 8.0,
                              'rpc_timeout': 30.0,
                              'task_deadline': 30.0,
                              'reconnect_initial_delay': 0.25,
                              'reconnect_max_delay': 2.0,
                              'reconnect_max_tries': 60}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, learner.num_episodes,
          learner.num_returned_episodes, flush=True)
    print('LEDGER', json.dumps(learner.ledger.stats), flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def _wait_for(predicate, deadline, poll=1.0):
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_chaos_remote_cluster_survives_faults(tmp_path):
    """A remote-cluster run with (a) the only gather SIGKILLed mid-run and
    (b) the data socket severed between epochs must still complete its
    2-epoch budget with converged accounting: the stranded tasks are
    re-issued, the respawned/reconnected gather resumes, and the learner
    finishes instead of hanging on episodes that will never arrive."""
    from tests.proxy import ChaosProxy

    entry_port, data_port = 21910, 21911
    model_dir = str(tmp_path / 'models')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {'model_dir': model_dir})
    worker_py.write_text(WORKER_SCRIPT)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'PYTHONPATH': repo + os.pathsep + os.environ.get('PYTHONPATH', '')}
    learner_env = {**base_env, 'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
                   'HANDYRL_TPU_DATA_PORT': str(data_port)}

    proxy = ChaosProxy(target_port=data_port)
    # the worker host dials the data port THROUGH the proxy (reconnects
    # included); chaos kills its single gather once, early in the run
    worker_env = {**base_env, 'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
                  'HANDYRL_TPU_DATA_PORT': str(proxy.port),
                  'HANDYRL_TPU_CHAOS': 'kill_gather=6,max_kills=1,seed=3'}

    learner_log = open(tmp_path / 'learner.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner = subprocess.Popen([sys.executable, str(learner_py)],
                               env=learner_env, stdout=learner_log,
                               stderr=subprocess.STDOUT)
    worker = None
    try:
        time.sleep(3)   # let the entry/data servers bind
        worker = subprocess.Popen([sys.executable, str(worker_py)],
                                  env=worker_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)

        def learner_says(needle):
            return needle in (tmp_path / 'learner.log').read_text()

        # generation is underway (minimum episodes reached), so the gather
        # holds prefetched/in-flight booked tasks more or less continuously
        assert _wait_for(
            lambda: learner_says('started training')
            or learner.poll() is not None, time.time() + 240), \
            'fleet never produced the minimum episodes'

        # fault 2: hard-sever every data connection, repeatedly, until the
        # gather demonstrably went through its supervised reconnect AND the
        # server stranded + re-issued booked tasks (the kill above may have
        # already produced the re-issue); after each cut the gather must
        # back off, redial (through the proxy) and resume — the run cannot
        # finish short of episodes, so severed outstanding work forces the
        # re-issue path
        def both_faults_observed():
            return ('reconnecting' in (tmp_path / 'worker.log').read_text()
                    and learner_says('re-issuing'))

        deadline = time.time() + 240
        while (not both_faults_observed()
               and learner.poll() is None and time.time() < deadline):
            proxy.sever()
            time.sleep(1.5)

        def done():
            return (os.path.exists(os.path.join(model_dir, '2.ckpt'))
                    or learner.poll() is not None)
        assert _wait_for(done, time.time() + 240), \
            'learner hung after injected faults'
        assert os.path.exists(os.path.join(model_dir, '2.ckpt')), \
            'run did not reach its epoch budget'

        # with training over, the whole actor tree must wind down on its
        # own: None tasks -> workers exit -> gathers exit 0 -> host exits
        learner.wait(timeout=120)
        worker.wait(timeout=120)
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        proxy.close()
        learner_log.close()
        worker_log.close()

    learner_out = (tmp_path / 'learner.log').read_text()
    worker_out = (tmp_path / 'worker.log').read_text()

    # the chaos kill actually happened and the supervisor recovered it
    assert 'chaos: killing gather' in worker_out
    assert 'respawning' in worker_out
    # the severed gather went through the supervised-reconnect path
    assert 'reconnecting' in worker_out
    # the learner noticed the dead peer and re-issued its booked tasks
    assert 'disconnected' in learner_out
    # only the LEDGER line itself: trailing diagnostics (e.g. the
    # graftlint-sanitizer exit report) may follow it in the stream
    ledger = json.loads(
        learner_out.split('LEDGER', 1)[1].strip().splitlines()[0])
    assert ledger['reissued'] >= 1, 'stranded tasks were never re-issued'
    assert ledger['completed'] <= ledger['assigned']

    # accounting converged: 2 epochs at minimum=12/update=12 means at least
    # 36 returned episodes actually fed training
    done_line = [l for l in learner_out.splitlines()
                 if l.startswith('LEARNER DONE')][0]
    _, _, epoch, num_episodes, num_returned = done_line.split()
    assert int(epoch) == 2
    assert int(num_returned) >= 36
