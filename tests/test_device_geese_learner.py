"""Learner end to end with device-resident Hungry Geese generation."""

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.models import build
from handyrl_tpu.train import Learner


def test_geese_device_learner_one_epoch(tmp_path):
    raw = {
        'env_args': {'env': 'HungryGeese'},
        'train_args': {
            'turn_based_training': False, 'observation': True,
            'gamma': 0.99, 'forward_steps': 8, 'compress_steps': 4,
            'batch_size': 8, 'update_episodes': 10, 'minimum_episodes': 10,
            'epochs': 1, 'generation_envs': 8, 'num_batchers': 1,
            'device_generation': True,
            'policy_target': 'VTRACE', 'value_target': 'VTRACE',
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args, net=build('GeeseNet', layers=2, filters=16))
    learner.run()
    assert learner.model_epoch == 1
    assert learner.num_returned_episodes >= 10
    assert (tmp_path / 'models' / '1.ckpt').exists()
