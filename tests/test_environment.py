"""Environment protocol tests.

Ports the reference's test strategy (`/root/reference/tests/
test_environment.py`): construction/property smoke, 100 random playouts
through the local interface, and the network-consistency oracle — per-player
mirror environments driven only by diff_info/update deltas and string actions
must agree with the master environment on legal-action sets every step.
"""

import importlib
import random

import pytest

ENVS = [
    'tictactoe',
    'parallel_tictactoe',
    'geister',
    'kaggle.hungry_geese',
    'kaggle.connectx',
]


def _make(env):
    try:
        module = importlib.import_module('handyrl_tpu.envs.' + env)
    except ModuleNotFoundError:
        pytest.skip('environment %s not implemented yet' % env)
    return module, module.Environment({})


@pytest.mark.parametrize('env', ENVS)
def test_environment_property(env):
    _, e = _make(env)
    assert len(e.players()) >= 1
    str(e)


@pytest.mark.parametrize('env', ENVS)
def test_environment_local(env):
    random.seed(0)
    _, e = _make(env)
    for _ in range(30):
        e.reset()
        steps = 0
        while not e.terminal():
            actions = {p: random.choice(e.legal_actions(p)) for p in e.turns()}
            e.step(actions)
            e.reward()
            steps += 1
            assert steps < 10000
        outcome = e.outcome()
        assert set(outcome.keys()) == set(e.players())


@pytest.mark.parametrize('env', ENVS)
def test_environment_network_consistency(env):
    random.seed(1)
    module, e = _make(env)
    mirrors = {p: module.Environment({}) for p in e.players()}
    for _ in range(30):
        e.reset()
        for p, m in mirrors.items():
            m.update(e.diff_info(p), True)
        while not e.terminal():
            actions = {}
            for player in e.turns():
                assert set(e.legal_actions(player)) == set(mirrors[player].legal_actions(player))
                action = random.choice(mirrors[player].legal_actions(player))
                actions[player] = mirrors[player].action2str(action, player)
            actions = {p: e.str2action(a, p) for p, a in actions.items()}
            e.step(actions)
            for p, m in mirrors.items():
                m.update(e.diff_info(p), False)
            e.reward()
        e.outcome()


@pytest.mark.parametrize('env', ['tictactoe', 'parallel_tictactoe', 'geister'])
def test_observation_shapes_stable(env):
    """Observations must keep a fixed shape/dtype across steps (XLA needs
    static shapes)."""
    import numpy as np
    random.seed(2)
    _, e = _make(env)
    e.reset()
    ref = e.observation(e.players()[0])
    ref_shapes = [(a.shape, a.dtype) for a in (ref.values() if isinstance(ref, dict) else [ref])]
    while not e.terminal():
        for p in e.players():
            obs = e.observation(p)
            arrs = obs.values() if isinstance(obs, dict) else [obs]
            assert [(a.shape, a.dtype) for a in arrs] == ref_shapes
        e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})
