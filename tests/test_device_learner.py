"""Learner end to end with fully device-resident generation."""

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def test_learner_device_generation(tmp_path):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 40, 'minimum_episodes': 40,
            'epochs': 2, 'generation_envs': 16, 'forward_steps': 8,
            'num_batchers': 1, 'device_generation': True,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.model_epoch == 2
    assert learner.num_returned_episodes >= 80
    assert (tmp_path / 'models' / '2.ckpt').exists()
