"""Stress tests: concurrent buffer feed/sample (the learner/batcher thread
pair) and window-selection fuzzing against batch invariants."""

import random
import threading
from collections import deque

import numpy as np

from handyrl_tpu.ops.batch import make_batch, select_episode
from helpers import turn_based_episode, train_args


def test_concurrent_feed_and_select():
    """Feeder thread extends/trims the deque while samplers select windows —
    the GIL-atomic deque contract the trainer relies on (reference
    train.py:472-483); IndexError is retried internally."""
    episodes = deque(turn_based_episode(6, seed=i) for i in range(50))
    args = train_args(forward_steps=4)
    args['maximum_episodes'] = 80
    stop = threading.Event()
    errors = []

    def feeder():
        i = 100
        while not stop.is_set():
            episodes.extend([turn_based_episode(6, seed=i)])
            i += 1
            while len(episodes) > 80:
                episodes.popleft()

    def sampler():
        try:
            for _ in range(300):
                w = select_episode(episodes, args)
                batch = make_batch([w], args)
                assert batch['observation'].shape[0] == 1
        except Exception as e:      # pragma: no cover
            errors.append(e)

    feed_thread = threading.Thread(target=feeder, daemon=True)
    sample_threads = [threading.Thread(target=sampler, daemon=True)
                      for _ in range(2)]
    feed_thread.start()
    for t in sample_threads:
        t.start()
    for t in sample_threads:
        t.join(timeout=120)
    stop.set()
    feed_thread.join(timeout=5)
    assert not errors, errors


def test_make_batch_fuzz_invariants():
    """Random episode lengths / window positions / burn-in: shapes and mask
    algebra must always hold."""
    random.seed(7)
    rng = np.random.RandomState(7)
    for trial in range(30):
        steps = rng.randint(1, 12)
        fs = rng.randint(1, 10)
        burn = rng.randint(0, 4)
        ep = turn_based_episode(steps, seed=trial)
        args = train_args(forward_steps=fs, burn_in=burn)
        w = select_episode([ep], args)
        batch = make_batch([w], args)

        T = burn + fs
        assert batch['observation'].shape[:3] == (1, T, 1)
        assert batch['turn_mask'].shape == (1, T, 2, 1)
        emask = batch['episode_mask'][0, :, 0, 0]
        tmask = batch['turn_mask'][0]
        omask = batch['observation_mask'][0]
        # outside the episode nothing is acted/observed
        assert np.all(tmask[emask == 0] == 0)
        assert np.all(omask[emask == 0] == 0)
        # inside the window exactly one player acts per step
        assert np.all(tmask.sum(axis=1)[emask == 1] == 1)
        # padded probs are exactly 1 (=> zero log-prob contribution)
        probs = batch['selected_prob'][0, :, 0, 0]
        assert np.all(probs[emask == 0] == 1.0)
        # progress within [0, 1]
        assert batch['progress'].min() >= 0.0
        assert batch['progress'].max() <= 1.0
