"""Hand-computed verification of the loss composition arithmetic."""

import numpy as np
import jax.numpy as jnp

from handyrl_tpu.ops.losses import LossConfig, compose_losses, optax_huber


def test_compose_losses_hand_case():
    """B=1, T=1, P=1, everything observable — every term computed by hand."""
    logits = np.array([[[[2.0, 1.0, 0.0]]]], np.float32)     # (1,1,1,3)
    value = np.array([[[[0.4]]]], np.float32)
    ret_out = np.array([[[[0.2]]]], np.float32)
    outputs = {'policy': jnp.asarray(logits), 'value': jnp.asarray(value),
               'return': jnp.asarray(ret_out)}

    logp_sel = np.array([[[[-0.3]]]], np.float32)
    adv = np.array([[[[0.5]]]], np.float32)
    targets = {'value': jnp.asarray([[[[0.9]]]], np.float32),
               'return': jnp.asarray([[[[2.0]]]], np.float32)}
    ones = np.ones((1, 1, 1, 1), np.float32)
    batch = {'turn_mask': jnp.asarray(ones),
             'observation_mask': jnp.asarray(ones),
             'progress': jnp.asarray(np.full((1, 1, 1), 0.5, np.float32))}

    cfg = LossConfig(entropy_regularization=0.1,
                     entropy_regularization_decay=0.2)
    losses, dcnt = compose_losses(outputs, jnp.asarray(logp_sel),
                                  jnp.asarray(adv), targets, batch, cfg)

    # policy: -logp * adv = 0.3 * 0.5
    np.testing.assert_allclose(float(losses['p']), 0.15, rtol=1e-6)
    # value: (0.4-0.9)^2 / 2
    np.testing.assert_allclose(float(losses['v']), 0.125, rtol=1e-6)
    # return: huber(0.2, 2.0) = |1.8| - 0.5 (linear regime)
    np.testing.assert_allclose(float(losses['r']), 1.3, rtol=1e-6)
    # entropy of softmax([2,1,0])
    e = np.exp([2.0, 1.0, 0.0])
    p = e / e.sum()
    ent = float(-(p * np.log(p)).sum())
    np.testing.assert_allclose(float(losses['ent']), ent, rtol=1e-5)
    # total = p + v + r - coef * ent * (1 - progress*(1-decay))
    decay_factor = 1 - 0.5 * (1 - 0.2)
    want_total = 0.15 + 0.125 + 1.3 - 0.1 * ent * decay_factor
    np.testing.assert_allclose(float(losses['total']), want_total, rtol=1e-5)
    assert float(dcnt) == 1.0


def test_huber_regimes():
    pred = jnp.asarray([0.0, 0.0, 0.0])
    target = jnp.asarray([0.5, 1.0, 3.0])
    got = np.asarray(optax_huber(pred, target))
    np.testing.assert_allclose(got, [0.125, 0.5, 2.5], rtol=1e-6)


def test_masked_entropy_is_zero_for_illegal_rows():
    """A fully-masked policy row (all logits -1e32 shifted) contributes ~0
    entropy and the masked player contributes nothing to p-loss."""
    logits = np.zeros((1, 1, 2, 4), np.float32)
    logits[0, 0, 1] = -1e32           # non-acting player's masked row
    outputs = {'policy': jnp.asarray(logits)}
    tmask = np.array([[[[1.0], [0.0]]]], np.float32)
    batch = {'turn_mask': jnp.asarray(tmask),
             'observation_mask': jnp.asarray(np.ones((1, 1, 2, 1), np.float32)),
             'progress': jnp.asarray(np.zeros((1, 1, 1), np.float32))}
    logp = jnp.asarray(np.zeros((1, 1, 2, 1), np.float32))
    adv = jnp.asarray(np.ones((1, 1, 2, 1), np.float32))
    losses, dcnt = compose_losses(outputs, logp, adv, {}, batch, LossConfig())
    assert np.isfinite(float(losses['total']))
    assert float(dcnt) == 1.0
    # uniform over 4 actions for the acting row
    np.testing.assert_allclose(float(losses['ent']), np.log(4.0), rtol=1e-5)
