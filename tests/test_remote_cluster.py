"""Remote-mode integration: a --train-server learner and a --worker host as
separate OS processes speaking the real TCP protocol (entry handshake on
:9999, gather data connections on :9998) on localhost."""

import os
import signal
import subprocess
import sys
import time

import pytest

LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 15,
                          'minimum_episodes': 15, 'epochs': 1,
                          'forward_steps': 8, 'num_batchers': 1,
                          'inference': {'enabled': %(engine)r},
                          'model_dir': %(model_dir)r}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


@pytest.mark.slow
@pytest.mark.timeout(600)
@pytest.mark.parametrize('engine', [False, True],
                         ids=['per-worker', 'inference-engine'])
def test_remote_train_server_and_worker(tmp_path, engine):
    model_dir = str(tmp_path / 'models')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {'model_dir': model_dir,
                                            'engine': engine})
    worker_py.write_text(WORKER_SCRIPT)

    env = {**os.environ, 'JAX_PLATFORMS': 'cpu'}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env['PYTHONPATH'] = repo + os.pathsep + env.get('PYTHONPATH', '')

    learner_log = open(tmp_path / 'learner.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner = subprocess.Popen([sys.executable, str(learner_py)], env=env,
                               stdout=learner_log, stderr=subprocess.STDOUT)
    try:
        time.sleep(3)   # let the entry/worker servers bind
        worker = subprocess.Popen([sys.executable, str(worker_py)], env=env,
                                  stdout=worker_log, stderr=subprocess.STDOUT)
        try:
            deadline = time.time() + 240
            done_path = os.path.join(model_dir, '1.ckpt')
            while time.time() < deadline:
                if os.path.exists(done_path):
                    break
                if learner.poll() is not None:
                    break
                time.sleep(2)
            assert os.path.exists(done_path), 'no checkpoint from remote training'
        finally:
            worker.send_signal(signal.SIGTERM)
            worker.wait(timeout=20)
    finally:
        if learner.poll() is None:
            learner.send_signal(signal.SIGTERM)
        try:
            learner.wait(timeout=20)
        except subprocess.TimeoutExpired:
            learner.kill()
