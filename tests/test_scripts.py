"""Tooling-script tests: SWA averaging, StableHLO export round-trip, and the
log-parsing plotters."""

import os
import sys

import numpy as np
import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), '..', 'scripts')
sys.path.insert(0, os.path.abspath(SCRIPTS))


@pytest.fixture(scope='module')
def trained_models(tmp_path_factory):
    """Two real checkpoints from a tiny training run."""
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    model_dir = str(tmp_path_factory.mktemp('swa') / 'models')
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 25, 'minimum_episodes': 30,
            'epochs': 2, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 1, 'model_dir': model_dir,
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    return model_dir


def test_swa_script(trained_models, monkeypatch):
    import aux_swa
    monkeypatch.setattr(sys, 'argv',
                        ['aux_swa.py', 'TicTacToe', '1', '2', trained_models])
    aux_swa.main()
    assert os.path.exists(os.path.join(trained_models, 'swa.ckpt'))
    # the average must differ from both endpoints
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import load_model
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    obs = env.observation(0)
    outs = [load_model(os.path.join(trained_models, name), env).inference(obs)['policy']
            for name in ('1.ckpt', '2.ckpt', 'swa.ckpt')]
    assert not np.allclose(outs[0], outs[2])


def test_export_script(trained_models, monkeypatch, tmp_path):
    import export_model
    out = str(tmp_path / 'model.jaxexp')
    monkeypatch.setattr(sys, 'argv',
                        ['export_model.py', 'TicTacToe',
                         os.path.join(trained_models, 'latest.ckpt'), out])
    export_model.main()

    from handyrl_tpu.environment import make_env
    from handyrl_tpu.evaluation import load_model
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    obs = env.observation(0)
    native = load_model(os.path.join(trained_models, 'latest.ckpt'), env)
    exported = load_model(out, env)
    np.testing.assert_allclose(exported.inference(obs)['policy'],
                               native.inference(obs)['policy'], atol=1e-4)


def test_plot_parsers(tmp_path):
    log = tmp_path / 'train.log'
    log.write_text(
        'waiting training\n'
        'epoch 1\n'
        'win rate = 0.500 (10.0 / 20)\n'
        'generation stats = 0.100 +- 0.900\n'
        'loss = ent:1.500 p:-0.250 total:0.125 v:0.200\n'
        'updated model(50)\n'
        'epoch 2\n'
        'win rate (random) = 0.650 (13.0 / 20)\n'
        'win rate (total) = 0.640 (12.8 / 20)\n'
        'generation stats = 0.150 +- 0.800\n'
        'loss = ent:1.400 p:-0.200 total:0.100 v:0.150\n'
    )
    import loss_plot
    import stats_plot
    import win_rate_plot

    _, series = win_rate_plot.parse(str(log))
    assert series['total'][0][1] == 0.5
    assert series['random'][0] == (2, 0.65, 20)

    losses = loss_plot.parse(str(log))
    assert losses['ent'] == [1.5, 1.4]
    assert losses['p'] == [-0.25, -0.2]

    stats = stats_plot.parse(str(log))
    assert stats == [(0.1, 0.9), (0.15, 0.8)]


def test_eval_checkpoints_script(trained_models, monkeypatch, tmp_path):
    """Offline checkpoint quality curve: one JSON row per checkpoint with a
    win rate from whole-match device evaluation; --skip-scored makes a
    rerun incremental (no duplicate {epoch, opponent} rows — the
    chip_window.sh once-per-tunnel-window contract)."""
    import json

    import eval_checkpoints
    out = str(tmp_path / 'curve.jsonl')
    monkeypatch.setattr(sys, 'argv',
                        ['eval_checkpoints.py', trained_models, 'TicTacToe',
                         out, '--every', '1', '--games', '12',
                         '--envs', '4'])
    eval_checkpoints.main()
    rows = [json.loads(l) for l in open(out)]
    assert [r['epoch'] for r in rows] == [1, 2]
    for r in rows:
        assert r['games'] >= 12 and 0.0 <= r['win_rate'] <= 1.0
        assert r['opponent'] == 'random'

    # rerun with --skip-scored: everything already scored -> no new rows
    monkeypatch.setattr(sys, 'argv',
                        ['eval_checkpoints.py', trained_models, 'TicTacToe',
                         out, '--every', '1', '--games', '12',
                         '--envs', '4', '--skip-scored'])
    eval_checkpoints.main()
    rows2 = [json.loads(l) for l in open(out)]
    assert [r['epoch'] for r in rows2] == [1, 2], \
        'skip-scored rerun must not append duplicate rows'

    # drop epoch 2's row: a rerun must score exactly the unscored epoch
    # (the incremental half of the contract — a skip-everything regression
    # would leave the file short)
    with open(out, 'w') as f:
        f.write(json.dumps(rows2[0]) + '\n')
    eval_checkpoints.main()
    rows3 = [json.loads(l) for l in open(out)]
    assert [r['epoch'] for r in rows3] == [1, 2], \
        'skip-scored rerun must evaluate epochs missing from the file'


def test_trace_report_json_schema(tmp_path, capsys):
    """scripts/trace_report.py --json output contract: every consumer-facing
    key present, stage/segment rows shaped {n, p50, p95}, and the
    exit-code contract (0 with a complete chain, 2 without)."""
    import json

    import trace_report

    def ev(name, ts, dur, pid, trace_id=None, trace_ids=None):
        args = {}
        if trace_id:
            args['trace_id'] = trace_id
        if trace_ids:
            args['trace_ids'] = trace_ids
        return json.dumps({'name': name, 'cat': 'handyrl', 'ph': 'X',
                           'ts': ts, 'dur': dur, 'pid': pid, 'tid': 1,
                           'args': args})

    trace = tmp_path / 'trace-run1.jsonl'
    trace.write_text('\n'.join([
        ev('task_assign', 1000, 10, 1, trace_id='g7'),
        ev('generate', 2000, 5000, 2, trace_id='g7'),
        ev('upload', 8000, 300, 3, trace_id='g7'),
        ev('ingest', 9000, 100, 1, trace_id='g7'),
        ev('train_step', 10000, 2000, 1, trace_ids=['g7']),
        ev('decode', 9500, 50, 1),
        '{torn half-line',
    ]) + '\n')

    assert trace_report.main([str(tmp_path), '--json']) == 0
    report = json.loads(capsys.readouterr().out)
    for key in ('events', 'processes', 'chains', 'complete_chains',
                'order_violations', 'stage_seconds', 'segment_seconds',
                'generation_to_gradient_seconds'):
        assert key in report, 'missing %r' % key
    assert report['events'] == 6
    assert report['processes'] == 3
    assert report['chains'] == 1
    assert report['complete_chains'] == 1
    assert report['order_violations'] == 0
    for table in ('stage_seconds', 'segment_seconds'):
        for name, row in report[table].items():
            assert set(row) == {'n', 'p50', 'p95'}, (table, name)
            assert row['n'] >= 1
    assert 'decode' in report['stage_seconds']
    g2g = report['generation_to_gradient_seconds']
    assert set(g2g) == {'n', 'p50', 'p95'}
    # generate start (ts=2000us) -> train_step end (12000us) = 10ms
    assert g2g['n'] == 1 and abs(g2g['p50'] - 0.01) < 1e-9

    # exit contract: an incomplete chain (no train_step) exits 2
    broken = tmp_path / 'broken'
    broken.mkdir()
    (broken / 'trace-run2.jsonl').write_text('\n'.join([
        ev('task_assign', 1000, 10, 1, trace_id='g9'),
        ev('generate', 2000, 5000, 2, trace_id='g9'),
    ]) + '\n')
    assert trace_report.main([str(broken), '--json']) == 2
    capsys.readouterr()
    # and an empty dir exits 2 without output
    empty = tmp_path / 'empty'
    empty.mkdir()
    assert trace_report.main([str(empty)]) == 2


def test_trace_report_serve_mode_and_require(tmp_path, capsys):
    """``--serve`` reduces the serving-path spans (hop percentiles, the
    per-replica queue/compute split, replay + reconstruction chains,
    session timelines) and flips the exit contract to "a complete serve
    chain exists"; ``--require`` picks the chain kind explicitly so a
    serve-only trace doesn't read as a training failure."""
    import json

    import trace_report

    def ev(name, ts, dur, pid, trace_id=None, trace_ids=None, **extra):
        args = dict(extra)
        if trace_id:
            args['trace_id'] = trace_id
        if trace_ids:
            args['trace_ids'] = trace_ids
        return json.dumps({'name': name, 'cat': 'handyrl', 'ph': 'X',
                           'ts': ts, 'dur': dur, 'pid': pid, 'tid': 1,
                           'args': args})

    trace = tmp_path / 'trace-serve1.jsonl'
    trace.write_text('\n'.join([
        # request r1: a complete routed chain crossing a failover replay
        # (the link span carries the ORIGINAL trace id)
        ev('client_request', 1000, 9000, 1, trace_id='r1'),
        ev('route_dispatch', 1200, 50, 1, trace_id='r1', replica='r0',
           breaker='closed'),
        ev('router_replay', 4000, 80, 1, trace_id='r1', link='replay',
           from_replica='r0', to_replica='r1'),
        ev('serve_request', 5000, 2000, 20, trace_id='r1', replica='r1'),
        ev('queue_wait', 5200, 300, 20, trace_id='r1'),
        ev('engine_batch', 5600, 900, 20, trace_ids=['r1']),
        # session s1: open + 2 plies + a journal reconstruction linked to
        # the session's open-time trace id
        ev('gateway_open', 500, 100, 3, trace_id='g1', sid='s1'),
        ev('gateway_ply', 2000, 400, 3, trace_id='p1', sid='s1',
           session_trace='g1'),
        ev('gateway_ply', 3000, 500, 3, trace_id='p2', sid='s1',
           session_trace='g1'),
        ev('gateway_reconstruct', 6000, 700, 3, trace_id='g1',
           link='reconstruct', sid='s1', replayed=2, ok=True),
    ]) + '\n')

    assert trace_report.main([str(tmp_path), '--serve', '--json']) == 0
    sv = json.loads(capsys.readouterr().out)['serve']
    assert sv['complete_chains'] == 1
    assert sv['routed_chains'] == 1
    assert sv['replay_chains'] == 1
    assert sv['complete_replay_chains'] == 1
    assert sv['reconstruct_chains'] == 1
    for name in ('client_request', 'route_dispatch', 'serve_request',
                 'queue_wait', 'engine_batch', 'gateway_open',
                 'gateway_ply'):
        row = sv['hop_seconds'][name]
        assert set(row) == {'n', 'p50', 'p95', 'p99'} and row['n'] >= 1
    # the queue-wait vs batch-compute split keys on the replica learned
    # from serve_request (the engine shares the service pid)
    assert sv['replica_split']['r1']['queue_wait']['n'] == 1
    assert sv['replica_split']['r1']['engine_batch']['n'] == 1
    assert sv['sessions']['s1']['plies'] == 2
    assert sv['sessions']['s1']['span_seconds'] == pytest.approx(0.0015)

    # exit contract: the default (training) still fails this serve-only
    # trace; --require any accepts either kind; --serve with an explicit
    # --require training renders the block but gates on training
    assert trace_report.main([str(tmp_path), '--json']) == 2
    capsys.readouterr()
    assert trace_report.main([str(tmp_path), '--json',
                              '--require', 'any']) == 0
    capsys.readouterr()
    assert trace_report.main([str(tmp_path), '--serve',
                              '--require', 'training']) == 2
    capsys.readouterr()
