"""On-device window assembly vs the host batch builder: exact parity.

Feeds the SAME synthetic episode through ops/batch.py build_window (host
reference path, itself pinned to reference train.py:33-124 semantics) and
ops/device_windows.py build_windows_{turn,solo}, for every train_start,
including the burn-in-pad and episode-tail-pad regimes.
"""

import numpy as np
import jax
import jax.numpy as jnp

from handyrl_tpu.ops.batch import build_window
from handyrl_tpu.ops.device_windows import (DeviceWindower,
                                            build_windows_solo,
                                            build_windows_turn,
                                            _discounted_returns)

FS, BI = 4, 2
L = 16
GAMMA = 0.8


def _turn_episode(S=10, A=5, P=2, seed=0):
    rng = np.random.RandomState(seed)
    obs = rng.rand(S, 3, 3, 3).astype(np.float32)
    prob = rng.uniform(0.1, 1.0, S).astype(np.float32)
    action = rng.randint(0, A, S).astype(np.int32)
    amask = np.where(rng.rand(S, A) < 0.3, 1e32, 0).astype(np.float32)
    value = rng.uniform(-1, 1, (S, 1)).astype(np.float32)
    player = (np.arange(S) % P).astype(np.int32)
    reward = rng.uniform(-0.1, 0.1, (S, P)).astype(np.float32)
    outcome = np.array([1.0, -1.0], np.float32)
    return dict(obs=obs, prob=prob, action=action, amask=amask, value=value,
                player=player, reward=reward, outcome=outcome, S=S, P=P)


def _host_moments(ep):
    """The episode in generator moment format (generation.py records)."""
    S, P = ep['S'], ep['P']
    rets = np.zeros((S, P), np.float32)
    acc = np.zeros(P, np.float32)
    for t in range(S - 1, -1, -1):
        acc = ep['reward'][t] + GAMMA * acc
        rets[t] = acc
    moments = []
    for t in range(S):
        p = int(ep['player'][t])
        m = {key: {q: None for q in range(P)} for key in
             ('observation', 'selected_prob', 'action_mask', 'action',
              'value', 'reward', 'return')}
        m['observation'][p] = ep['obs'][t]
        m['selected_prob'][p] = float(ep['prob'][t])
        m['action_mask'][p] = ep['amask'][t]
        m['action'][p] = int(ep['action'][t])
        m['value'][p] = ep['value'][t]
        m['reward'] = {q: float(ep['reward'][t, q]) for q in range(P)}
        m['return'] = {q: float(rets[t, q]) for q in range(P)}
        m['turn'] = [p]
        moments.append(m)
    return moments, rets


def _turn_hist(ep):
    S = ep['S']
    pad = lambda a: np.concatenate(
        [a, np.zeros((L - S,) + a.shape[1:], a.dtype)])
    valid = np.arange(L) < S
    rew = pad(ep['reward'])
    ret = np.asarray(_discounted_returns(jnp.asarray(rew),
                                         jnp.asarray(valid), GAMMA))
    return {'obs': jnp.asarray(pad(ep['obs'])),
            'prob': jnp.asarray(pad(ep['prob'])),
            'action': jnp.asarray(pad(ep['action'])),
            'amask': jnp.asarray(pad(ep['amask'])),
            'value': jnp.asarray(pad(ep['value'])),
            'player': jnp.asarray(pad(ep['player'])),
            'reward': jnp.asarray(rew),
            'return': jnp.asarray(ret)}


ARGS = {'turn_based_training': True, 'observation': False,
        'forward_steps': FS, 'burn_in_steps': BI}


def test_turn_mode_matches_host_builder_every_train_start():
    ep = _turn_episode()
    moments, _ = _host_moments(ep)
    hist = _turn_hist(ep)
    S = ep['S']
    for ts in range(1 + max(0, S - FS)):
        st = max(0, ts - BI)
        ed = min(ts + FS, S)
        meta = {'outcome': {0: 1.0, 1: -1.0}, 'start': st, 'end': ed,
                'train_start': ts, 'total': S}
        host = build_window(moments[st:ed], meta, ARGS)
        dev = build_windows_turn(hist, jnp.int32(S),
                                 jnp.asarray([ts], jnp.int32),
                                 jnp.asarray(ep['outcome']), FS, BI, L,
                                 ep['P'])
        for key in host:
            h = np.asarray(host[key], np.float32)
            d = np.asarray(dev[key][0], np.float32)
            np.testing.assert_allclose(
                d, h, rtol=1e-5, atol=1e-6,
                err_msg='turn mode key=%s train_start=%d' % (key, ts))


def _solo_episode(S=9, A=4, P=3, seed=3):
    rng = np.random.RandomState(seed)
    acting = rng.rand(S, P) < 0.7
    acting[:, 0] = True   # keep at least one actor per ply
    return dict(
        obs=rng.rand(S, P, 2, 3, 3).astype(np.float32),
        prob=rng.uniform(0.1, 1.0, (S, P)).astype(np.float32),
        action=rng.randint(0, A, (S, P)).astype(np.int32),
        amask=np.where(rng.rand(S, P, A) < 0.3, 1e32, 0).astype(np.float32),
        value=rng.uniform(-1, 1, (S, P, 1)).astype(np.float32),
        acting=acting,
        reward=rng.uniform(-0.1, 0.1, (S, P)).astype(np.float32),
        outcome=np.array([1.0, -1 / 3, -2 / 3], np.float32), S=S, P=P)


def _solo_moments(ep):
    S, P = ep['S'], ep['P']
    rets = np.zeros((S, P), np.float32)
    acc = np.zeros(P, np.float32)
    for t in range(S - 1, -1, -1):
        acc = ep['reward'][t] + GAMMA * acc
        rets[t] = acc
    moments = []
    for t in range(S):
        m = {key: {q: None for q in range(P)} for key in
             ('observation', 'selected_prob', 'action_mask', 'action',
              'value', 'reward', 'return')}
        actors = []
        for p in range(P):
            if not ep['acting'][t, p]:
                continue
            actors.append(p)
            m['observation'][p] = ep['obs'][t, p]
            m['selected_prob'][p] = float(ep['prob'][t, p])
            m['action_mask'][p] = ep['amask'][t, p]
            m['action'][p] = int(ep['action'][t, p])
            m['value'][p] = ep['value'][t, p]
        m['reward'] = {q: float(ep['reward'][t, q]) for q in range(P)}
        m['return'] = {q: float(rets[t, q]) for q in range(P)}
        m['turn'] = actors
        moments.append(m)
    return moments


def _solo_hist(ep):
    S = ep['S']
    pad = lambda a: np.concatenate(
        [a, np.zeros((L - S,) + a.shape[1:], a.dtype)])
    valid = np.arange(L) < S
    rew = pad(ep['reward'])
    ret = np.asarray(_discounted_returns(jnp.asarray(rew),
                                         jnp.asarray(valid), GAMMA))
    return {'obs': jnp.asarray(pad(ep['obs'])),
            'prob': jnp.asarray(pad(ep['prob'])),
            'action': jnp.asarray(pad(ep['action'])),
            'amask': jnp.asarray(pad(ep['amask'])),
            'value': jnp.asarray(pad(ep['value'])),
            'acting': jnp.asarray(pad(ep['acting'])),
            'reward': jnp.asarray(rew),
            'return': jnp.asarray(ret)}


SOLO_ARGS = {'turn_based_training': False, 'observation': True,
             'forward_steps': FS, 'burn_in_steps': BI}


def test_solo_mode_matches_host_builder(monkeypatch):
    ep = _solo_episode()
    moments = _solo_moments(ep)
    hist = _solo_hist(ep)
    S, P = ep['S'], ep['P']
    for seat in range(P):
        # pin the host builder's random seat choice to `seat`
        import random as _random
        monkeypatch.setattr(_random, 'choice', lambda seq: seat)
        for ts in range(1 + max(0, S - FS)):
            st = max(0, ts - BI)
            ed = min(ts + FS, S)
            meta = {'outcome': {q: float(ep['outcome'][q]) for q in range(P)},
                    'start': st, 'end': ed, 'train_start': ts, 'total': S}
            host = build_window(moments[st:ed], meta, SOLO_ARGS)
            dev = build_windows_solo(hist, jnp.int32(S),
                                     jnp.asarray([ts], jnp.int32),
                                     jnp.asarray([seat], jnp.int32),
                                     jnp.asarray(ep['outcome']), FS, BI, L)
            for key in host:
                h = np.asarray(host[key], np.float32)
                d = np.asarray(dev[key][0], np.float32)
                np.testing.assert_allclose(
                    d, h, rtol=1e-5, atol=1e-6,
                    err_msg='solo key=%s seat=%d ts=%d' % (key, seat, ts))


def test_ingest_fills_ring_and_counts_episodes():
    """End-to-end chunk ingestion: two tiny turn-based envs, deterministic
    done pattern, ring receives windows and episode counts add up."""
    K, N, A, P, S = 6, 2, 3, 2, 3   # every env finishes every 3 plies
    rng = np.random.RandomState(1)
    records = {
        'obs': jnp.asarray(rng.rand(K, N, 2, 2).astype(np.float32)),
        'prob': jnp.asarray(rng.uniform(0.2, 1, (K, N)).astype(np.float32)),
        'action': jnp.asarray(rng.randint(0, A, (K, N)).astype(np.int32)),
        'amask': jnp.asarray(np.zeros((K, N, A), np.float32)),
        'value': jnp.asarray(rng.rand(K, N, 1).astype(np.float32)),
        'player': jnp.asarray((np.indices((K, N))[0] % P).astype(np.int32)),
        'done': jnp.asarray((np.indices((K, N))[0] % S) == S - 1),
        'outcome': jnp.asarray(
            np.tile(np.array([1., -1.], np.float32), (K, N, 1))),
    }
    wd = DeviceWindower(mode='turn', fs=2, bi=0, max_steps=8, windows_cap=2,
                        capacity=32, num_players=P, gamma=GAMMA,
                        has_reward=False)
    state = wd.init_state(records)
    ring = wd.init_ring(records)
    state, ring, cursor, size, key, n_done, n_windows = wd.ingest(
        records, state, ring, jnp.int32(0), jnp.int32(0),
        jax.random.PRNGKey(0))
    # 2 envs x 2 episodes each completed in 6 plies
    assert int(n_done) == 4
    assert int(n_windows) == 4
    assert int(size) == 4   # S//fs = 1 window per episode
    assert int(cursor) == 4
    # ring rows are stored flat (TPU tile-padding); unflatten to inspect
    got = wd.unflatten_rows(
        jax.tree_util.tree_map(lambda b: np.asarray(b[:4]), ring))
    assert got['observation'].shape == (4, 2, 1, 2, 2)
    assert got['turn_mask'].shape == (4, 2, P, 1)
    # every stored window is fully inside its episode (fs=2 <= S=3)
    assert np.all(got['episode_mask'] == 1.0)
    # counts reset after each done
    assert np.all(np.asarray(state['counts']) == 0)


def test_ingest_with_pytree_observations():
    """Dict observations (geister's {'scalar','board'}) flow through the
    windower: history buffers map over leaves, ring rows use dotted keys,
    and unflatten_rows rebuilds the nested batch pytree."""
    K, N, A, P, S = 6, 2, 3, 2, 3
    rng = np.random.RandomState(2)
    records = {
        'obs': {'scalar': jnp.asarray(rng.rand(K, N, 5).astype(np.float32)),
                'board': jnp.asarray(
                    rng.rand(K, N, 2, 2, 2).astype(np.float32))},
        'prob': jnp.asarray(rng.uniform(0.2, 1, (K, N)).astype(np.float32)),
        'action': jnp.asarray(rng.randint(0, A, (K, N)).astype(np.int32)),
        'amask': jnp.asarray(np.zeros((K, N, A), np.float32)),
        'value': jnp.asarray(rng.rand(K, N, 1).astype(np.float32)),
        'player': jnp.asarray((np.indices((K, N))[0] % P).astype(np.int32)),
        'done': jnp.asarray((np.indices((K, N))[0] % S) == S - 1),
        'outcome': jnp.asarray(
            np.tile(np.array([1., -1.], np.float32), (K, N, 1))),
    }
    wd = DeviceWindower(mode='turn', fs=2, bi=0, max_steps=8, windows_cap=2,
                        capacity=32, num_players=P, gamma=GAMMA,
                        has_reward=False)
    state = wd.init_state(records)
    ring = wd.init_ring(records)
    assert 'observation.scalar' in ring and 'observation.board' in ring
    state, ring, cursor, size, key, n_done, n_windows = wd.ingest(
        records, state, ring, jnp.int32(0), jnp.int32(0),
        jax.random.PRNGKey(0))
    assert int(n_done) == 4 and int(size) == 4
    got = wd.unflatten_rows(
        jax.tree_util.tree_map(lambda b: np.asarray(b[:4]), ring))
    # nested batch pytree restored, window shapes intact
    assert set(got['observation']) == {'scalar', 'board'}
    assert got['observation']['scalar'].shape == (4, 2, 1, 5)
    assert got['observation']['board'].shape == (4, 2, 1, 2, 2, 2)
    assert got['turn_mask'].shape == (4, 2, P, 1)
    # stored board content matches the recorded plies for a full window:
    # env 0's first episode occupies plies 0..2; window start is 0 or 1
    src = np.asarray(records['obs']['board'])[:, 0]
    win = got['observation']['board'][:, :, 0]
    found = any(
        np.allclose(win[i], src[st:st + 2])
        for i in range(4) for st in (0, 1))
    assert found


def test_flatten_window_keys_arbitrary_depth_roundtrip():
    """ADVICE r4: deeper-than-one dict nesting must roundtrip (or fail
    fast), not leak dict values into the ring."""
    import pytest
    from handyrl_tpu.ops.device_windows import (flatten_window_keys,
                                                unflatten_window_keys)
    win = {
        'action': np.zeros((2, 3), np.int32),
        'observation': {'board': np.ones((2, 4)),
                        'aux': {'inner': np.full((2, 1), 7.0),
                                'deep': {'leaf': np.zeros((2, 2))}}},
    }
    flat = flatten_window_keys(win)
    assert set(flat) == {'action', 'observation.board',
                         'observation.aux.inner',
                         'observation.aux.deep.leaf'}
    back = unflatten_window_keys(flat)
    assert back['observation']['aux']['deep']['leaf'].shape == (2, 2)
    np.testing.assert_array_equal(back['observation']['aux']['inner'],
                                  win['observation']['aux']['inner'])

    with pytest.raises(AssertionError, match='reserved'):
        flatten_window_keys({'observation': {'bad.key': np.zeros(2)}})
    with pytest.raises(AssertionError, match='not an array'):
        flatten_window_keys({'observation': {'v': [1, 2, 3]}})
