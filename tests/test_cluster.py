"""Fake-cluster integration test: the 4-RPC worker protocol end to end.

Runs the Learner in worker-process mode (batched_generation off): learner
server -> gather processes -> worker processes over spawn+pipes, one training
epoch, model snapshots fetched over the wire.
"""

import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


@pytest.mark.timeout(600)
def test_local_worker_cluster_one_epoch(tmp_path):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 8, 'update_episodes': 20, 'minimum_episodes': 20,
            'epochs': 1, 'forward_steps': 8, 'num_batchers': 1,
            'batched_generation': False,
            'worker': {'num_parallel': 2},
            'model_dir': str(tmp_path / 'models'),
        },
    }
    args = apply_defaults(raw)
    learner = Learner(args=args)
    learner.run()
    assert learner.model_epoch == 1
    assert learner.num_returned_episodes >= 20
    assert (tmp_path / 'models' / '1.ckpt').exists()
