"""Unified telemetry: registry math, merge rules, heartbeat piggyback over a
real Hub pair, Prometheus exposition, the append-safe JSONL sink, and (slow)
the distributed learner+worker run whose metrics_jsonl carries the merged
fleet aggregates the exporter also serves.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from handyrl_tpu import telemetry
from handyrl_tpu.telemetry import (MetricRegistry, TelemetryExporter,
                                   hist_quantile, merge_snapshots,
                                   metric_key, relabel, render_prometheus,
                                   split_key, summarize,
                                   validate_metrics_line)


# ---------------------------------------------------------------------------
# registry


def test_counter_concurrent_increments():
    reg = MetricRegistry()
    c = reg.counter('requests_total', role='g')

    def spin():
        for _ in range(5000):
            c.inc()

    threads = [threading.Thread(target=spin) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40000
    assert reg.snapshot()['counters']['requests_total{role="g"}'] == 40000


def test_metric_handles_are_cached_and_labeled():
    reg = MetricRegistry()
    assert reg.counter('a_total', x=1) is reg.counter('a_total', x=1)
    assert reg.counter('a_total', x=1) is not reg.counter('a_total', x=2)
    assert metric_key('a_total', {'b': 2, 'a': 1}) == 'a_total{a="1",b="2"}'
    assert split_key('a_total{a="1"}') == ('a_total', 'a="1"')
    assert split_key('plain') == ('plain', '')


def test_gauge_set_and_add():
    reg = MetricRegistry()
    g = reg.gauge('depth')
    g.set(3)
    g.add(2)
    assert reg.snapshot()['gauges']['depth'] == 5.0


def test_histogram_buckets_and_percentiles():
    reg = MetricRegistry()
    h = reg.histogram('lat_seconds', buckets=(0.01, 0.1, 1.0), stage='x')
    for v in (0.005, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = reg.snapshot()['hists']['lat_seconds{stage="x"}']
    assert snap['buckets'] == [2, 1, 1, 1]     # one overflow bucket
    assert snap['count'] == 5
    assert abs(snap['sum'] - 5.56) < 1e-9
    # p50: rank 2.5 inside the first bucket (2 events, bounds 0..0.01)
    assert 0.0 < h.quantile(0.5) <= 0.1
    # p99 lands in the overflow bucket -> clamped to the last bound
    assert h.quantile(0.99) == 1.0
    # empty histogram quantile is defined
    assert hist_quantile((1.0,), [0, 0], 0, 0.5) == 0.0


def test_histogram_observe_agg_matches_sums():
    reg = MetricRegistry()
    h = reg.histogram('stage_seconds', stage='decode')
    h.observe_agg(0.5, 10)                      # 10 events, 50ms mean
    assert h.count == 10
    assert abs(h.sum - 0.5) < 1e-12


def test_snapshot_reset_semantics():
    reg = MetricRegistry()
    reg.counter('c_total').inc(7)
    reg.gauge('g').set(4)
    reg.histogram('h_seconds').observe(0.2)
    first = reg.snapshot(reset=True)
    assert first['counters']['c_total'] == 7
    second = reg.snapshot()
    assert second['counters']['c_total'] == 0   # counters restart
    assert second['hists']['h_seconds']['count'] == 0
    assert second['gauges']['g'] == 4.0         # gauges are levels, kept


def test_disabled_registry_is_inert(monkeypatch):
    monkeypatch.setattr(telemetry, '_ENABLED', False)
    reg = MetricRegistry()
    reg.counter('c_total').inc(5)
    reg.gauge('g').set(1)
    reg.histogram('h').observe(1.0)
    snap = reg.snapshot()
    assert snap['counters']['c_total'] == 0
    assert snap['gauges']['g'] == 0.0
    assert snap['hists']['h']['count'] == 0


def test_span_records_stage_histogram():
    reg = MetricRegistry()
    with reg.span('select'):
        time.sleep(0.01)
    with reg.span('decode', parent='select'):
        pass
    snap = reg.snapshot()
    h = snap['hists']['stage_seconds{stage="select"}']
    assert h['count'] == 1 and h['sum'] >= 0.01
    assert 'stage_seconds{parent="select",stage="decode"}' in snap['hists']


def test_stage_timer_mirrors_into_registry():
    from handyrl_tpu.utils.timing import StageTimer
    reg = MetricRegistry()
    timer = StageTimer(registry=reg)
    timer.add('assemble', 0.25, count=5)
    assert timer.snapshot()['assemble'] == {'s': 0.25, 'n': 5}
    h = reg.snapshot()['hists']['stage_seconds{stage="assemble"}']
    assert h['count'] == 5 and abs(h['sum'] - 0.25) < 1e-9


# ---------------------------------------------------------------------------
# merge rules


def _snap(counters=None, gauges=None, hists=None):
    return {'run_id': 'x', 'time': 0.0, 'counters': counters or {},
            'gauges': gauges or {}, 'hists': hists or {}}


def test_merge_counters_sum_gauges_sum_hists_add():
    h = {'bounds': [0.1, 1.0], 'buckets': [1, 2, 0], 'sum': 1.5, 'count': 3}
    a = _snap({'c_total': 2}, {'depth{gather="0"}': 3.0}, {'lat': dict(h)})
    b = _snap({'c_total': 5}, {'depth{gather="1"}': 4.0}, {'lat': dict(h)})
    merged = merge_snapshots([a, b, None, 'garbage'])
    assert merged['peers'] == 2                 # non-dicts skipped
    assert merged['counters']['c_total'] == 7
    # distinct label sets stay distinct (per-gather resolution survives)
    assert merged['gauges'] == {'depth{gather="0"}': 3.0,
                                'depth{gather="1"}': 4.0}
    assert merged['hists']['lat']['buckets'] == [2, 4, 0]
    assert merged['hists']['lat']['count'] == 6


def test_merge_skips_mismatched_bucket_bounds():
    a = _snap(hists={'lat': {'bounds': [0.1], 'buckets': [1, 0],
                             'sum': 0.05, 'count': 1}})
    b = _snap(hists={'lat': {'bounds': [0.2], 'buckets': [3, 0],
                             'sum': 0.3, 'count': 3}})
    merged = merge_snapshots([a, b])
    assert merged['hists']['lat']['count'] == 1   # peer with other bounds skipped


def test_merge_counts_mismatched_bucket_bounds():
    """The disagree path must drop-with-counter, never mis-add: the first
    peer's histogram survives untouched, every later disagreeing peer is
    counted — as a merged COUNTER (so the signal survives re-merging up
    the fleet tree and reaches the exposition) and as a top-level field."""
    a = _snap(hists={'lat': {'bounds': [0.1, 1.0], 'buckets': [1, 0, 0],
                             'sum': 0.05, 'count': 1}})
    b = _snap(hists={'lat': {'bounds': [0.2, 1.0], 'buckets': [3, 0, 0],
                             'sum': 0.3, 'count': 3}})
    c = _snap(hists={'lat': {'bounds': [0.1], 'buckets': [5, 0],
                             'sum': 0.5, 'count': 5}})
    merged = merge_snapshots([a, b, c])
    # first peer wins the geometry; neither disagreeing peer was mis-added
    assert merged['hists']['lat']['bounds'] == [0.1, 1.0]
    assert merged['hists']['lat']['buckets'] == [1, 0, 0]
    assert merged['hists']['lat']['count'] == 1
    assert abs(merged['hists']['lat']['sum'] - 0.05) < 1e-12
    assert merged['hist_bound_conflicts'] == 2
    assert merged['counters']['telemetry_hist_bound_conflicts_total'] == 2
    # the conflict counter itself re-merges like any flow
    again = merge_snapshots([merged, merged])
    assert again['counters']['telemetry_hist_bound_conflicts_total'] == 4
    # agreeing peers still add and report no conflict
    clean = merge_snapshots([a, a])
    assert clean['hists']['lat']['count'] == 2
    assert 'hist_bound_conflicts' not in clean
    assert 'telemetry_hist_bound_conflicts_total' not in clean['counters']


def test_summarize_reduces_histograms():
    h = {'bounds': [0.1, 1.0], 'buckets': [8, 1, 1], 'sum': 2.0, 'count': 10}
    out = summarize(_snap({'c_total': 1}, {'g': 2.0}, {'lat': h}))
    assert out['counters'] == {'c_total': 1}
    assert set(out['hists']['lat']) == {'count', 'sum', 'p50', 'p95', 'p99'}
    assert out['hists']['lat']['count'] == 10


# ---------------------------------------------------------------------------
# heartbeat piggyback through a real Hub pair


def test_heartbeat_piggyback_roundtrip_through_hub():
    """A worker/gather registry snapshot must survive the msgpack wire codec
    inside a heartbeat frame and come back out of peer_info ready to merge —
    exactly the path worker -> gather -> learner telemetry rides."""
    import socket
    from handyrl_tpu.connection import FramedConnection, HEARTBEAT_KIND, Hub

    reg = MetricRegistry()
    reg.counter('gather_uploads_total', gather='3', kind='episode').inc(12)
    reg.gauge('gather_episodes_per_sec', gather='3').set(2.5)
    reg.histogram('worker_task_seconds', role='g').observe(0.05)
    snap = reg.snapshot()

    hub = Hub()
    a, b = socket.socketpair()
    server_side, client_side = FramedConnection(a), FramedConnection(b)
    hub.attach(server_side)
    client_side.send((HEARTBEAT_KIND,
                      {'gather': 3, 'reconnects': 0, 'telemetry': snap}))
    deadline = time.time() + 10
    info = {}
    while time.time() < deadline:
        info = hub.peer_info_snapshot().get(server_side) or {}
        if info:
            break
        time.sleep(0.05)
    assert info.get('gather') == 3
    merged = merge_snapshots([info.get('telemetry')])
    key = 'gather_uploads_total{gather="3",kind="episode"}'
    assert merged['counters'][key] == 12
    assert merged['gauges']['gather_episodes_per_sec{gather="3"}'] == 2.5
    assert merged['hists']['worker_task_seconds{role="g"}']['count'] == 1
    hub.detach(server_side)
    client_side.close()


# ---------------------------------------------------------------------------
# Prometheus exposition + HTTP exporter


_PROM_LINE = re.compile(
    r'^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(_bucket|_sum|_count)?'
    r'(\{[^{}]*\})? [0-9eE.+-]+)$')


def assert_valid_exposition(body: str):
    lines = [l for l in body.splitlines() if l.strip()]
    assert lines, 'empty exposition'
    for line in lines:
        assert _PROM_LINE.match(line), 'bad exposition line: %r' % line


def test_render_prometheus_format():
    reg = MetricRegistry()
    reg.counter('requests_total', role='g').inc(3)
    reg.gauge('depth').set(1.5)
    reg.histogram('lat_seconds', buckets=(0.1, 1.0)).observe(0.05)
    body = render_prometheus([reg.snapshot()])
    assert_valid_exposition(body)
    assert '# TYPE requests_total counter' in body
    assert 'requests_total{role="g"} 3' in body
    assert 'depth 1.5' in body
    # histogram: cumulative buckets + +Inf + sum/count
    assert 'lat_seconds_bucket{le="0.1"} 1' in body
    assert 'lat_seconds_bucket{le="+Inf"} 1' in body
    assert 'lat_seconds_count 1' in body


def test_exporter_falls_back_to_ephemeral_port():
    """A busy telemetry_port must not crash the learner: the exporter
    retries, falls back to an ephemeral port, logs the real one (kept on
    .port) and counts the fallback."""
    reg = MetricRegistry()
    reg.counter('pings_total').inc(1)
    blocker = TelemetryExporter(lambda: [reg.snapshot()], port=0).start()
    try:
        busy_port = blocker.port
        before = telemetry.counter('telemetry_port_fallbacks_total').value
        exporter = TelemetryExporter(lambda: [reg.snapshot()],
                                     port=busy_port).start()
        try:
            assert exporter.port != busy_port and exporter.port > 0
            assert telemetry.counter(
                'telemetry_port_fallbacks_total').value == before + 1
            body = urllib.request.urlopen(
                'http://127.0.0.1:%d/metrics' % exporter.port,
                timeout=10).read().decode()
            assert 'pings_total 1' in body
        finally:
            exporter.stop()
    finally:
        blocker.stop()


def test_exporter_serves_metrics_over_http():
    reg = MetricRegistry()
    reg.counter('pings_total').inc(2)
    fleet = relabel(reg.snapshot(), source='fleet')
    exporter = TelemetryExporter(
        lambda: [reg.snapshot(), fleet], port=0).start()
    try:
        url = 'http://127.0.0.1:%d/metrics' % exporter.port
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        assert_valid_exposition(body)
        assert 'pings_total 2' in body
        assert 'pings_total{source="fleet"} 2' in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                'http://127.0.0.1:%d/nope' % exporter.port, timeout=10)
    finally:
        exporter.stop()


# ---------------------------------------------------------------------------
# flight recorder + blackbox dumps


def test_flight_recorder_ring_bounds_and_stats():
    rec = telemetry.FlightRecorder(capacity=16)
    for i in range(40):
        rec.record('test', 'event %d' % i, i=i)
    st = rec.stats()
    assert st['events'] == 16 and st['total'] == 40 and st['dropped'] == 24
    assert rec.capacity == 16
    assert [e['i'] for e in rec.events()] == list(range(24, 40))


def test_flight_recorder_set_capacity_keeps_newest():
    rec = telemetry.FlightRecorder(capacity=64)
    for i in range(40):
        rec.record('test', 'e', i=i)
    rec.set_capacity(16)
    assert [e['i'] for e in rec.events()] == list(range(24, 40))


def test_flight_recorder_dump_schema(tmp_path):
    rec = telemetry.FlightRecorder(capacity=16)
    rec.record('guard', 'something tripped', detail=7)
    path = rec.dump('unit-test', directory=str(tmp_path),
                    context={'k': 1})
    assert path and os.path.exists(path)
    payload = json.load(open(path))
    assert payload['schema'] == 'handyrl_tpu.blackbox/1'
    assert payload['reason'] == 'unit-test'
    assert payload['context'] == {'k': 1}
    assert payload['pid'] == os.getpid()
    assert payload['events'][-1]['msg'] == 'something tripped'
    assert path in rec.stats()['dumps']
    # an empty directory disables dumping entirely
    assert rec.dump('unit-test', directory='') is None


def test_flight_recorder_disabled_is_inert(monkeypatch):
    rec = telemetry.FlightRecorder(capacity=16)
    monkeypatch.setattr(telemetry, '_ENABLED', False)
    rec.record('test', 'dropped')
    assert rec.stats()['total'] == 0


def test_recorder_only_toggle_leaves_metrics_live():
    rec = telemetry.FlightRecorder(capacity=16)
    telemetry.set_recorder_enabled(False)
    try:
        rec.record('test', 'dropped')
        telemetry.counter('recorder_toggle_probe_total').inc()
    finally:
        telemetry.set_recorder_enabled(True)
    assert rec.stats()['total'] == 0
    assert telemetry.counter('recorder_toggle_probe_total').value == 1
    rec.record('test', 'kept')
    assert rec.stats()['total'] == 1


def test_log_warnings_land_in_recorder():
    # compare the monotonic total, not a kind-filtered length: once the
    # ring reaches capacity (easy in a long suite run) every append
    # evicts an old event and the filtered count stays flat
    before = telemetry.recorder_stats()['total']
    telemetry.get_logger('recorder-test').warning('recorder mirror check')
    assert telemetry.recorder_stats()['total'] > before
    logged = [e for e in telemetry.recorder().events()
              if e.get('kind') == 'log']
    assert any('recorder mirror check' in e['msg'] for e in logged)


# ---------------------------------------------------------------------------
# SLO alert engine


def _gauge_snap(**gauges):
    return [{'counters': {}, 'gauges': dict(gauges), 'hists': {}}]


def _counter_snap(**counters):
    return [{'counters': dict(counters), 'gauges': {}, 'hists': {}}]


def test_alert_value_rule_sustain_and_clear_debounce():
    eng = telemetry.AlertEngine([
        {'name': 'deep_queue', 'metric': 'q_depth', 'kind': 'value',
         'op': '>', 'threshold': 5.0, 'for': 10.0, 'clear_for': 5.0}])
    blk = eng.evaluate(_gauge_snap(q_depth=9.0), now=100.0)
    assert blk['active'] == []                 # must sustain 10 s first
    blk = eng.evaluate(_gauge_snap(q_depth=9.0), now=111.0)
    assert blk['active'] == ['deep_queue']
    assert blk['fired'] == {'deep_queue': 1}
    assert telemetry.gauge('alerts_active', alert='deep_queue').value == 1
    blk = eng.evaluate(_gauge_snap(q_depth=1.0), now=112.0)
    assert blk['active'] == ['deep_queue']     # clear_for debounce holds
    blk = eng.evaluate(_gauge_snap(q_depth=1.0), now=120.0)
    assert blk['active'] == []
    assert telemetry.gauge('alerts_active', alert='deep_queue').value == 0


def test_alert_rate_rule_needs_two_samples():
    eng = telemetry.AlertEngine([
        {'name': 'err_burst', 'metric': 'errs_total', 'kind': 'rate',
         'op': '>', 'threshold': 1.0}])
    assert eng.evaluate(_counter_snap(errs_total=0),
                        now=10.0)['active'] == []
    blk = eng.evaluate(_counter_snap(errs_total=30), now=20.0)   # 3/s
    assert blk['active'] == ['err_burst']
    assert blk['values']['err_burst'] == 3.0


def test_alert_ratio_rule_burn_rate():
    eng = telemetry.AlertEngine([
        {'name': 'shed_burn', 'metric': 'shed_total', 'kind': 'ratio',
         'denominator': 'reqs_total', 'op': '>', 'threshold': 0.05}])
    eng.evaluate(_counter_snap(shed_total=0, reqs_total=0), now=0.0)
    blk = eng.evaluate(_counter_snap(shed_total=10, reqs_total=100),
                       now=10.0)
    assert blk['active'] == ['shed_burn']      # 10% of requests shed


def test_alert_arm_metric_gates_until_first_signal():
    eng = telemetry.AlertEngine([
        {'name': 'stall', 'metric': 'eps_total', 'kind': 'rate',
         'op': '<=', 'threshold': 0.0, 'arm_metric': 'eps_total'}])
    empty = _counter_snap()
    assert eng.evaluate(empty, now=1.0)['active'] == []
    assert eng.evaluate(empty, now=2.0)['active'] == []    # still unarmed
    live = _counter_snap(eps_total=5)
    eng.evaluate(live, now=3.0)
    blk = eng.evaluate(live, now=4.0)          # armed; zero rate breaches
    assert blk['active'] == ['stall']


def test_alert_engine_from_config_merge_and_disable():
    eng = telemetry.AlertEngine.from_config({'telemetry': {'alerts': {
        'rules': [
            {'name': 'ingest_stall', 'threshold': 1.0},
            {'name': 'custom_rule', 'metric': 'q_depth', 'kind': 'value',
             'op': '>', 'threshold': 2.0}]}}})
    names = eng.rule_names()
    assert 'custom_rule' in names
    assert names.count('ingest_stall') == 1    # override, not duplicate
    builtin = {str(s['name']) for s in telemetry.BUILTIN_ALERTS}
    assert builtin <= set(names)
    assert telemetry.AlertEngine.from_config(
        {'telemetry': {'alerts': False}}) is None
    assert telemetry.AlertEngine.from_config({'telemetry': False}) is None


def test_alert_maybe_evaluate_is_cadence_gated():
    eng = telemetry.AlertEngine([
        {'name': 'deep_queue', 'metric': 'q_depth', 'kind': 'value',
         'op': '>', 'threshold': 5.0}], interval=5.0)
    calls = []

    def collect():
        calls.append(1)
        return _gauge_snap(q_depth=9.0)

    eng.maybe_evaluate(collect, now=100.0)
    eng.maybe_evaluate(collect, now=101.0)     # inside the cadence window
    assert len(calls) == 1
    blk = eng.maybe_evaluate(collect, now=106.0)
    assert len(calls) == 2
    assert blk['active'] == ['deep_queue']


# ---------------------------------------------------------------------------
# status surface (/healthz, /statusz, main.py --status)


def test_exporter_serves_healthz_and_statusz():
    reg = MetricRegistry()
    exporter = TelemetryExporter(
        lambda: [reg.snapshot()], port=0,
        status=lambda: {'progress': {'epoch': 3},
                        'alerts': {'active': ['ingest_stall']}}).start()
    try:
        base = 'http://127.0.0.1:%d' % exporter.port
        assert urllib.request.urlopen(
            base + '/healthz', timeout=10).read() == b'ok\n'
        payload = json.loads(urllib.request.urlopen(
            base + '/statusz', timeout=10).read().decode())
        assert payload['progress'] == {'epoch': 3}
        assert payload['alerts']['active'] == ['ingest_stall']
        assert payload['pid'] == os.getpid()
        assert 'run_id' in payload and 'recorder' in payload
        rendered = telemetry.render_status(payload)
        assert 'ingest_stall' in rendered
        fetched = telemetry.fetch_statusz('127.0.0.1:%d' % exporter.port)
        assert fetched['pid'] == os.getpid()
    finally:
        exporter.stop()


# ---------------------------------------------------------------------------
# append-safe JSONL + schema checker


def test_append_jsonl_writes_complete_lines(tmp_path):
    from handyrl_tpu.utils.fs import append_jsonl
    path = str(tmp_path / 'metrics.jsonl')
    for i in range(3):
        append_jsonl(path, {'epoch': i, 'v': 'x' * 100})
    lines = open(path).read().splitlines()
    assert [json.loads(l)['epoch'] for l in lines] == [0, 1, 2]


def test_rotate_file_caps_metrics_jsonl(tmp_path):
    from handyrl_tpu.utils.fs import rotate_file
    path = str(tmp_path / 'metrics.jsonl')
    with open(path, 'w') as f:
        f.write('x' * 2048)
    assert not rotate_file(path, 1.0)          # under the cap: untouched
    assert not rotate_file(path, 0)            # 0 = rotation off
    assert rotate_file(path, 0.001)            # ~1 KB cap: rotate
    assert not os.path.exists(path)
    assert os.path.getsize(path + '.1') == 2048
    assert not rotate_file(path, 0.001)        # gone now: nothing to do


def test_validate_metrics_line_schema():
    good = json.dumps({'epoch': 1, 'steps': 10, 'episodes': 100,
                       'time': 1.0, 'run_id': 'abc',
                       'telemetry': {'counters': {}, 'gauges': {},
                                     'hists': {}}})
    rec = validate_metrics_line(good)
    assert rec['epoch'] == 1
    with pytest.raises(ValueError):
        validate_metrics_line(json.dumps({'epoch': 1}))
    with pytest.raises(ValueError):
        validate_metrics_line(good, fleet=True)   # no fleet_telemetry key


# ---------------------------------------------------------------------------
# distributed e2e: fleet aggregation lands in metrics_jsonl + the exporter


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 2,
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': %(model_dir)r,
                          'metrics_jsonl': %(metrics)r,
                          'telemetry_port': %(port)d,
                          'fault_tolerance': {'heartbeat_interval': 1.0,
                                              'liveness_timeout': 15.0}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_distributed_run_exports_fleet_telemetry(tmp_path):
    """Learner + worker host over real TCP: per-epoch metrics_jsonl records
    must carry merged fleet telemetry (per-gather episodes/sec, upload
    counters, queue depths) consistent with the per-process snapshots, and
    the Prometheus endpoint must serve valid exposition text while the run
    is live."""
    entry_port, data_port, prom_port = 22910, 22911, 22912
    model_dir = str(tmp_path / 'models')
    metrics = str(tmp_path / 'metrics.jsonl')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {
        'model_dir': model_dir, 'metrics': metrics, 'port': prom_port})
    worker_py.write_text(WORKER_SCRIPT)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'PYTHONPATH': repo + os.pathsep
                + os.environ.get('PYTHONPATH', ''),
                'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
                'HANDYRL_TPU_DATA_PORT': str(data_port)}

    learner_log = open(tmp_path / 'learner.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner = subprocess.Popen([sys.executable, str(learner_py)],
                               env=base_env, stdout=learner_log,
                               stderr=subprocess.STDOUT)
    worker = None
    exposition = ''
    try:
        time.sleep(3)
        worker = subprocess.Popen([sys.executable, str(worker_py)],
                                  env=base_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)
        # scrape the exporter while the run is alive (retry until up)
        deadline = time.time() + 240
        url = 'http://127.0.0.1:%d/metrics' % prom_port
        while time.time() < deadline and learner.poll() is None:
            try:
                exposition = urllib.request.urlopen(
                    url, timeout=5).read().decode()
                if 'source="fleet"' in exposition:
                    break
            except OSError:
                pass
            time.sleep(2)
        learner.wait(timeout=300)
        worker.wait(timeout=120)
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.kill()
        learner_log.close()
        worker_log.close()

    assert_valid_exposition(exposition)
    assert 'source="fleet"' in exposition, \
        'exporter never served merged fleet metrics'

    lines = [l for l in open(metrics).read().splitlines() if l.strip()]
    assert lines, 'no metrics_jsonl records written'
    last = None
    for line in lines:
        last = validate_metrics_line(line, fleet=True)
    fleet = last['fleet_telemetry']
    # the acceptance trio: episodes/sec per gather (gauge), RPC retry
    # counters, and upload/queue depth gauges, all merged from heartbeats
    assert any(k.startswith('gather_episodes_per_sec')
               for k in fleet['gauges']), fleet['gauges']
    assert any(k.startswith('gather_upload_box_depth')
               for k in fleet['gauges'])
    assert any(k.startswith('gather_rpc_retries_total')
               for k in fleet['counters'])
    uploads = sum(v for k, v in fleet['counters'].items()
                  if k.startswith('gather_uploads_total'))
    assert uploads > 0
    # fleet episode counters are plausible against the learner's own view
    assert last['episodes'] >= 24
