"""Action-agreement tests for the HungryGeese rule-based opponent.

The reference's ``rule_based_action`` delegates to kaggle_environments'
GreedyAgent (reference hungry_geese.py:189-197). Our env ports that agent's
decision rules; this file checks the port two ways:

* scripted boards exercising each documented rule (food seeking, reversal
  ban, head-adjacency avoidance, body avoidance, eating-tail avoidance);
* a fuzz sweep comparing every move of random games against an independent
  transcription of the kaggle algorithm kept here as the oracle.
"""

import random

from handyrl_tpu.envs.kaggle.hungry_geese import (ACTIONS, C, Environment, R,
                                                  _move)

# kaggle's Action enum order — candidate scan and tie-break order
_K_ORDER = ['NORTH', 'EAST', 'SOUTH', 'WEST']
_K_DELTA = {'NORTH': (-1, 0), 'EAST': (0, 1), 'SOUTH': (1, 0),
            'WEST': (0, -1)}
_K_OPPOSITE = {'NORTH': 'SOUTH', 'SOUTH': 'NORTH',
               'EAST': 'WEST', 'WEST': 'EAST'}


def kaggle_greedy_oracle(env, player):
    """Transcription of GreedyAgent.__call__ from kaggle_environments.

    Returns the action index in env's ACTIONS order, or None when the
    kaggle agent would fall back to a uniformly random action.
    """
    geese, food = env.geese, env.food

    def translate(pos, name):
        dr, dc = _K_DELTA[name]
        r, c = divmod(pos, C)
        return ((r + dr) % R) * C + (c + dc) % C

    def adjacent(pos):
        return [translate(pos, name) for name in _K_ORDER]

    def min_distance(pos):
        r, c = divmod(pos, C)
        return min(abs(r - fr) + abs(c - fc)
                   for f in food for fr, fc in [divmod(f, C)])

    opponents = [g for i, g in enumerate(geese) if i != player and len(g) > 0]
    head_adjacent_positions = {adj for opp in opponents
                               for head in [opp[0]] for adj in adjacent(head)}
    bodies = {pos for g in geese for pos in g[0:-1]}   # tails are steppable
    tails = {opp[-1] for opp in opponents for head in [opp[0]]
             if any(adj in food for adj in adjacent(head))}

    last = env.last_actions.get(player)
    last_name = ACTIONS[last] if last is not None else None

    position = geese[player][0]
    candidates = {name: min_distance(translate(position, name))
                  for name in _K_ORDER
                  if translate(position, name) not in head_adjacent_positions
                  and translate(position, name) not in bodies
                  and translate(position, name) not in tails
                  and (last_name is None
                       or name != _K_OPPOSITE[last_name])}
    if not candidates:
        return None
    return ACTIONS.index(min(candidates, key=candidates.get))


def _board(geese, food, last_actions=None):
    env = Environment({'id': 0})
    env.geese = [list(g) for g in geese]
    env.alive = [bool(g) for g in geese]
    env.food = list(food)
    env.last_actions = dict(last_actions or {})
    env.prev_geese = [list(g) for g in geese]
    return env


def cell(r, c):
    return r * C + c


def test_moves_toward_food():
    env = _board([[cell(3, 3)], [], [], []], [cell(3, 7), cell(0, 0)])
    assert ACTIONS[env.rule_based_action(0)] == 'EAST'
    env = _board([[cell(3, 3)], [], [], []], [cell(6, 3)])
    # food 3 rows south (non-wrapped metric: SOUTH shortens, NORTH doesn't)
    assert ACTIONS[env.rule_based_action(0)] == 'SOUTH'


def test_never_reverses_even_for_food():
    # moving EAST, food directly behind: reversal (WEST) is banned
    env = _board([[cell(3, 3), cell(3, 2)], [], [], []], [cell(3, 1)],
                 {0: ACTIONS.index('EAST')})
    assert ACTIONS[env.rule_based_action(0)] != 'WEST'


def test_avoids_opponent_head_adjacency():
    # food to the EAST, but an opponent head sits beyond it: the food cell
    # is head-adjacent, so the greedy agent detours
    env = _board([[cell(3, 3)], [cell(3, 5)], [], []], [cell(3, 4)])
    assert ACTIONS[env.rule_based_action(0)] != 'EAST'


def test_avoids_bodies_including_tails():
    # opponent body (incl. tail) due EAST; food beyond it
    env = _board([[cell(3, 3)], [cell(2, 4), cell(3, 4), cell(4, 4)], [], []],
                 [cell(3, 6)])
    assert ACTIONS[env.rule_based_action(0)] != 'EAST'


def test_avoids_tail_of_opponent_about_to_eat():
    # opponent head adjacent to food keeps its tail this turn: the tail
    # cell is excluded even though tails are otherwise vacated
    opp = [cell(0, 6), cell(0, 5), cell(0, 4), cell(1, 4), cell(2, 4),
           cell(3, 4)]
    env = _board([[cell(3, 3)], opp, [], []], [cell(0, 7)])
    a = env.rule_based_action(0)
    assert _move(cell(3, 3), a) != opp[-1]


def test_steps_onto_vacating_opponent_tail():
    # opponent head nowhere near food: its tail vacates this turn and IS a
    # legal (and here optimal) destination — kaggle's bodies exclude tails
    opp = [cell(1, 5), cell(2, 5), cell(3, 5), cell(3, 4)]
    env = _board([[cell(3, 3)], opp, [], []], [cell(4, 4)])
    assert ACTIONS[env.rule_based_action(0)] == 'EAST'


def test_steps_onto_own_vacating_tail():
    # own tail is never in the blocked set (and own goose is not an
    # opponent, so no eating-tail exclusion applies)
    own = [cell(3, 3), cell(2, 3), cell(2, 4), cell(3, 4)]
    env = _board([own, [], [], []], [cell(3, 5)],
                 {0: ACTIONS.index('SOUTH')})
    assert ACTIONS[env.rule_based_action(0)] == 'EAST'


def test_tie_break_follows_kaggle_enum_order():
    # food equidistant NORTH and SOUTH: kaggle scans NORTH first
    env = _board([[cell(3, 3)], [], [], []], [cell(1, 3), cell(5, 3)])
    assert ACTIONS[env.rule_based_action(0)] == 'NORTH'


def test_fuzz_agreement_with_kaggle_transcription():
    rng = random.Random(7)
    checked = 0
    for game in range(25):
        env = Environment({'id': game})
        env.reset()
        while not env.terminal():
            for p in env.turns():
                expected = kaggle_greedy_oracle(env, p)
                got = env.rule_based_action(p)
                if expected is None:      # kaggle falls back to random
                    assert 0 <= got < 4
                else:
                    assert got == expected, (
                        'disagreement at step %d player %d: ours %s, '
                        'kaggle %s' % (env.step_count, p, ACTIONS[got],
                                       ACTIONS[expected]))
                    checked += 1
            env.step({p: rng.randrange(4) for p in env.turns()})
    assert checked > 300   # the sweep actually exercised the agreement


def test_rulebase_games_complete():
    env = Environment({'id': 1})
    for _ in range(5):
        env.reset()
        while not env.terminal():
            env.step({p: env.rule_based_action(p) for p in env.turns()})
        outcome = env.outcome()
        assert abs(sum(outcome.values())) < 1e-9
