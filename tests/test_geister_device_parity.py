"""Pin the observation=True semantics for turn-based device rollouts (the
geister-device config) to the reference's.

The reference generator runs inference only for ``turn_players + observers``
(reference generation.py:37-41) and NO reference env overrides
``observers()`` (defaults to [], reference environment.py:84); the eval-side
Agent advances hidden only on its own turns (reference evaluation.py:97-101).
So observation=True does NOT mean "everyone observes every ply" — it only
widens the batch layout to the full player axis (reference train.py:65-68)
with observation_mask marking the acting seat. These tests assert the device
engine records exactly that, and that a device-generated Geister episode is
batch-level indistinguishable from a host-generated one."""

import numpy as np
import pytest

from handyrl_tpu.device_generation import DeviceEvaluator, DeviceGenerator
from handyrl_tpu.environment import make_env
from handyrl_tpu.envs import jax_geister as jgs
from handyrl_tpu.generation import Generator
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.geister import GeisterNet
from handyrl_tpu.ops.batch import decompress_moments, make_batch, select_episode


def _obs_args():
    return {
        'turn_based_training': True, 'observation': True,
        'gamma': 0.9, 'forward_steps': 8, 'burn_in_steps': 2,
        'compress_steps': 4, 'maximum_episodes': 100,
        'lambda': 0.7, 'policy_target': 'TD', 'value_target': 'TD',
        'entropy_regularization': 0.1, 'entropy_regularization_decay': 0.1,
    }


def _wrapper():
    env = make_env({'env': 'Geister'})
    env.reset()
    w = ModelWrapper(GeisterNet(filters=8, drc_layers=2, drc_repeats=1))
    w.ensure_params(env.observation(0))
    return w


@pytest.fixture(scope='module')
def episode_pair():
    """(wrapper, device episodes, one host episode) under the same config."""
    wrapper = _wrapper()
    args = _obs_args()
    gen = DeviceGenerator(jgs, wrapper, args, n_envs=4, chunk_steps=16)
    dev_episodes = []
    for _ in range(30):
        dev_episodes += gen.step_chunk()
        if len(dev_episodes) >= 2:
            break
    assert len(dev_episodes) >= 2, 'device generator produced no episodes'

    env = make_env({'env': 'Geister'})
    host_gen = Generator(env, args)
    models = {p: wrapper for p in (0, 1)}
    host_ep = None
    for _ in range(5):
        host_ep = host_gen.generate(models, {
            'role': 'g', 'player': [0, 1], 'model_id': {0: -1, 1: -1}})
        if host_ep is not None:
            break
    assert host_ep is not None
    return wrapper, dev_episodes, host_ep


def _assert_acting_seat_only(moments):
    for m in moments:
        player = m['turn'][0]
        other = 1 - player
        # exactly the acting seat observed, acted, and has a value estimate
        assert m['observation'][player] is not None
        assert m['value'][player] is not None
        assert m['action'][player] is not None
        assert m['selected_prob'][player] is not None
        assert m['action_mask'][player] is not None
        assert m['observation'][other] is None
        assert m['value'][other] is None
        assert m['action'][other] is None
        assert m['selected_prob'][other] is None
        assert m['action_mask'][other] is None


def test_device_moments_match_reference_semantics(episode_pair):
    _, dev_episodes, host_ep = episode_pair
    for ep in dev_episodes[:2]:
        moments = decompress_moments(ep['moment'])
        assert len(moments) == ep['steps']
        _assert_acting_seat_only(moments)
    _assert_acting_seat_only(decompress_moments(host_ep['moment']))


def test_device_batch_matches_host_batch(episode_pair):
    """Batch-level parity through ops/batch.py: same leaf set, same shapes
    (modulo batch size), same mask semantics — observation_mask is the
    acting-seat one-hot (== turn_mask), padded windows honor the same pad
    values."""
    _, dev_episodes, host_ep = episode_pair
    args = _obs_args()

    def invariants(batch):
        emask = np.asarray(batch['episode_mask'])      # (B, T, 1, 1)
        omask = np.asarray(batch['observation_mask'])  # (B, T, 2, 1)
        tmask = np.asarray(batch['turn_mask'])
        assert omask.shape[2] == 2
        # observers() is empty for Geister: who observed == who acted
        np.testing.assert_array_equal(omask, tmask)
        # exactly one acting seat per in-episode step
        np.testing.assert_array_equal(
            tmask.sum(axis=2)[..., 0], emask[:, :, 0, 0])
        # non-observers' observations are zero
        board = np.asarray(batch['observation']['board'])  # (B,T,2,7,6,6)
        dead = (omask[..., None, None] == 0)
        assert np.abs(board * dead[:, :, :board.shape[2]]).max() == 0
        # selected_prob pads/non-actors are 1 (log prob 0)
        prob = np.asarray(batch['selected_prob'])
        np.testing.assert_array_equal(prob[np.asarray(tmask) == 0], 1.0)

    dev_batch = make_batch(
        [select_episode(dev_episodes, args) for _ in range(4)], args)
    host_batch = make_batch(
        [select_episode([host_ep], args) for _ in range(2)], args)
    invariants(dev_batch)
    invariants(host_batch)
    assert set(dev_batch.keys()) == set(host_batch.keys())
    for k in dev_batch:
        if k == 'observation':
            for leaf in dev_batch[k]:
                assert dev_batch[k][leaf].shape[1:] == host_batch[k][leaf].shape[1:]
        else:
            assert dev_batch[k].shape[1:] == host_batch[k].shape[1:], k


def test_device_evaluator_hidden_advances_only_on_own_turns():
    """Reference eval parity: the Agent's hidden advances only when it acts
    (observers() is empty), so the device evaluator's acting-seat hidden
    gather/scatter is exactly right — and matches should complete."""
    wrapper = _wrapper()
    ev = DeviceEvaluator(jgs, wrapper, _obs_args(), n_envs=4, chunk_steps=16)
    results = []
    for _ in range(30):
        results += ev.step()
        if results:
            break
    assert results, 'device evaluator finished no matches'
    for res in results[:5]:
        seat = res['args']['player'][0]
        assert res['opponent'] == 'random'
        assert set(res['result'].keys()) == {0, 1}
        assert res['result'][seat] in (-1.0, 0.0, 1.0)
