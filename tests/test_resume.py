"""Checkpoint/resume test: a restarted learner continues the optimization
trajectory (params AND optimizer state/steps), not just the weights."""

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


def _args(model_dir, epochs, restart=0):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 25, 'minimum_episodes': 30,
            'epochs': epochs, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 1, 'model_dir': model_dir,
            'restart_epoch': restart,
        },
    }
    return apply_defaults(raw)


def test_resume_continues_trainer_state(tmp_path):
    model_dir = str(tmp_path / 'models')

    first = Learner(args=_args(model_dir, epochs=2))
    first.run()
    steps_before = first.trainer.steps
    assert steps_before > 0

    second = Learner(args=_args(model_dir, epochs=3, restart=2))
    # optimizer state and step counter restored before any new training
    # (saved at the last epoch boundary; the live counter may have ticked
    # a little further before shutdown)
    assert 0 < second.trainer.steps <= steps_before
    assert second.model_epoch == 2
    import numpy as np
    import jax
    mu_norm = sum(float(np.abs(np.asarray(l)).sum())
                  for l in jax.tree_util.tree_leaves(second.trainer.state.opt_state))
    assert mu_norm > 0, 'adam moments must be restored, not zero-initialized'

    second.run()
    assert second.model_epoch == 3
    assert second.trainer.steps > steps_before
