"""Checkpoint/resume test: a restarted learner continues the optimization
trajectory (params AND optimizer state/steps), not just the weights.

Each learner runs in a SPAWNED subprocess (same containment as
test_checkpoint_interval): the resume path has triggered heap corruption
inside XLA CPU on some hosts, and an in-process crash would kill the whole
pytest run — hiding every later test file — instead of failing one test.
"""

import json
import multiprocessing as mp
import os

import pytest

from handyrl_tpu.config import apply_defaults


def _args(model_dir, epochs, restart=0):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 25, 'minimum_episodes': 30,
            'epochs': epochs, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 1, 'model_dir': model_dir,
            'restart_epoch': restart,
        },
    }
    return apply_defaults(raw)


def _learner_child(args, report_path):
    # keep the child off the persistent XLA compile cache: jaxlib 0.4.x CPU
    # corrupts the heap (malloc abort / SIGSEGV) deserializing the cached
    # fused-pipeline executable on the resume run; these programs compile in
    # seconds, so the child just recompiles
    os.environ['HANDYRL_TPU_NO_COMPILE_CACHE'] = '1'
    import numpy as np
    import jax
    from handyrl_tpu.train import Learner
    ln = Learner(args=args)
    rep = {'steps_at_start': ln.trainer.steps,
           'model_epoch_at_start': ln.model_epoch}
    if ln.trainer.state is not None:
        rep['opt_mu_norm'] = sum(
            float(np.abs(np.asarray(l)).sum())
            for l in jax.tree_util.tree_leaves(ln.trainer.state.opt_state))
    ln.run()
    rep['model_epoch'] = ln.model_epoch
    rep['steps'] = ln.trainer.steps
    with open(report_path, 'w') as f:
        json.dump(rep, f)


def _run_learner(args, tmp, tag, timeout=480):
    report = os.path.join(tmp, 'resume_report_%s.json' % tag)
    ctx = mp.get_context('spawn')
    proc = ctx.Process(target=_learner_child, args=(args, report))
    proc.start()
    proc.join(timeout=timeout)
    if proc.is_alive():
        proc.terminate()
        proc.join(10)
        pytest.fail('learner subprocess timed out (%s)' % tag)
    # report written after ln.run() => contract completed even if the
    # interpreter aborted at teardown (known XLA daemon-thread issue)
    if not os.path.exists(report):
        pytest.fail('learner subprocess died with exit code %s (%s) — '
                    'backend crash, see stderr above' % (proc.exitcode, tag))
    with open(report) as f:
        return json.load(f)


@pytest.mark.timeout(560)
def test_resume_continues_trainer_state(tmp_path):
    model_dir = str(tmp_path / 'models')

    rep1 = _run_learner(_args(model_dir, epochs=2), str(tmp_path), 'first')
    steps_before = rep1['steps']
    assert steps_before > 0

    rep2 = _run_learner(_args(model_dir, epochs=3, restart=2),
                        str(tmp_path), 'resume')
    # optimizer state and step counter restored before any new training
    # (saved at the last epoch boundary; the live counter may have ticked
    # a little further before shutdown)
    assert 0 < rep2['steps_at_start'] <= steps_before
    assert rep2['model_epoch_at_start'] == 2
    assert rep2['opt_mu_norm'] > 0, \
        'adam moments must be restored, not zero-initialized'
    assert rep2['model_epoch'] == 3
    assert rep2['steps'] > steps_before
