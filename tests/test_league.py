"""League training (league.py): PFSP opponent sampling over the model
registry, the persistent Elo RatingBook, the rating-gated promotion path,
GC pinning of pool members, ledger re-issue stickiness of server-stamped
opponent assignments, and the server-stamped opponent override on the
worker-mode Evaluator — plus the ConnectX adapter that gives the league a
fourth environment. The slow test at the bottom is the full e2e: a real
TCP fleet with league.enabled, a SIGTERM/restart that preserves ratings,
and a promotion landing in the registry manifest."""

import copy
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from handyrl_tpu import league
from handyrl_tpu.config import apply_defaults
from handyrl_tpu.environment import make_env
from handyrl_tpu.fault import TaskLedger
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.serving.registry import ModelRegistry
from handyrl_tpu.utils.fs import checksummed_write_bytes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ttt_wrapper(seed=7):
    env = make_env({'env': 'TicTacToe'})
    env.reset()
    w = ModelWrapper(env.net(), seed=seed)
    w.ensure_params(env.observation(0))
    return env, w


# ---------------------------------------------------------------------------
# PFSP weighting curves


def test_pfsp_variance_prefers_even_matches():
    w = league.pfsp_weights([0.0, 0.5, 1.0], curve='variance')
    assert w.shape == (3,)
    assert w[1] > w[0] and w[1] > w[2]
    assert (w > 0).all()          # the floor keeps everyone reachable


def test_pfsp_hard_prefers_strong_opponents():
    w = league.pfsp_weights([0.1, 0.5, 0.9], curve='hard', hard_exponent=2.0)
    assert w[0] > w[1] > w[2]
    # a larger exponent sharpens the preference for the hardest member
    sharp = league.pfsp_weights([0.1, 0.5, 0.9], curve='hard',
                                hard_exponent=4.0)
    assert sharp[0] / sharp[1] > w[0] / w[1]


def test_pfsp_uniform_and_unknown_curve():
    w = league.pfsp_weights([0.0, 0.3, 1.0], curve='uniform')
    assert np.allclose(w, w[0])
    with pytest.raises(ValueError):
        league.pfsp_weights([0.5], curve='nope')


def test_member_name_round_trip():
    assert league.member_name('default', 3) == 'default@3'
    assert league.split_member('default@3') == ('default', '3')
    assert league.split_member('a@b@c') == ('a@b', 'c')
    assert league.split_member('random') == (None, None)


# ---------------------------------------------------------------------------
# sampling: deterministic per (seed, sample_key), audited seed machinery


def _pool_with_versions(root, versions, **overrides):
    reg = ModelRegistry(str(root))
    _, w = _ttt_wrapper()
    for v in versions:
        path = os.path.join(str(root), '%d.ckpt' % v)
        checksummed_write_bytes(path, w.params_bytes())
        reg.publish('default', path=path, architecture='SimpleConv2dModel',
                    version=v, promote=(v == versions[0]))
    args = dict(apply_defaults({'env_args': {'env': 'TicTacToe'}})
                ['train_args']['league'])
    args.update(overrides)
    pool = league.LeaguePool(args, 'default')
    pool.refresh(reg)
    return pool, reg


def test_sample_opponent_is_deterministic_and_diverse(tmp_path):
    pool, _ = _pool_with_versions(tmp_path, [1, 2], self_play_rate=0.0,
                                  curve='uniform')
    book = league.RatingBook()
    draws = [pool.sample_opponent(11, k, book) for k in range(200)]
    again = [pool.sample_opponent(11, k, book) for k in range(200)]
    assert draws == again                       # pure function of the task
    assert None not in draws                    # self_play_rate 0: all member
    assert {'default@1', 'default@2'} <= set(draws)
    # a different base seed is a different (still deterministic) sequence
    other = [pool.sample_opponent(12, k, book) for k in range(200)]
    assert other != draws


def test_sample_opponent_self_play_share(tmp_path):
    pool, _ = _pool_with_versions(tmp_path, [1], self_play_rate=1.0)
    book = league.RatingBook()
    assert all(pool.sample_opponent(0, k, book) is None for k in range(50))


def test_rating_opponent_round_robin_covers_roster(tmp_path):
    pool, _ = _pool_with_versions(tmp_path, [1, 2])
    roster = pool.roster()
    assert 'random' in roster
    seen = [pool.rating_opponent(i) for i in range(2 * len(roster))]
    assert seen[:len(roster)] == roster
    assert seen == roster + roster              # coverage, not exploration


def test_member_model_ids(tmp_path):
    pool, _ = _pool_with_versions(tmp_path, [1, 2])
    assert pool.member_model_id('default@2') == 2
    assert pool.member_model_id(league.RANDOM_ANCHOR) == 0
    assert pool.member_model_id('rulebase') is None


def test_refresh_keeps_champion_outside_member_window(tmp_path):
    # max_members 2 would drop v1 by recency, but v1 is the champion
    pool, _ = _pool_with_versions(tmp_path, [1, 2, 3, 4], max_members=2)
    assert pool.champion == 'default@1'
    assert 'default@1' in pool.members()
    assert {'default@3', 'default@4'} <= set(pool.members())
    assert 'default@2' not in pool.members()


# ---------------------------------------------------------------------------
# Elo rating book


def test_elo_win_raises_learner_and_mirrors_member():
    book = league.RatingBook(track_sigma=False, k_factor=32.0)
    book.record('m', 1.0)
    assert book.rating(league.LEARNER) == pytest.approx(1216.0)
    assert book.rating('m') == pytest.approx(1184.0)   # mirrored delta
    book.record('m', 0.0)
    # the loss moves more than the win did (learner was favored)
    assert book.rating(league.LEARNER) < 1200.0
    assert book.win_rate('m') == pytest.approx(0.5)
    assert book.games('m') == 2
    assert book.games_since_promote == 2


def test_sigma_shrinks_with_games_and_scales_k():
    book = league.RatingBook(track_sigma=True, initial_sigma=200.0,
                             min_sigma=50.0)
    for _ in range(100):
        book.record('m', 1.0)
    e = book.entry('m')
    assert e['sigma'] == pytest.approx(
        max(50.0, 200.0 / np.sqrt(1.0 + 100 / 8.0)))
    assert e['sigma'] < 200.0
    # a settled entry moves less per game than a fresh one
    settled = abs(book._k(e) - book.k_factor)
    assert book._k(e) < book.k_factor
    assert book._k({'sigma': 200.0}) == book.k_factor
    assert settled > 0


def test_journal_round_trip_is_bit_identical(tmp_path):
    path = str(tmp_path / 'ratings.json')
    book = league.RatingBook()
    for i in range(17):
        book.record('default@%d' % (i % 3), (i % 5) / 4.0)
    book.note_promotion()
    book.record('random', 0.5)
    book.save(path)
    raw = open(path, 'rb').read()

    clone = league.RatingBook()
    assert clone.load(path)
    clone.save(str(tmp_path / 'again.json'))
    assert open(str(tmp_path / 'again.json'), 'rb').read() == raw

    # the restored book reproduces subsequent updates bit-identically
    book.record('default@1', 1.0)
    clone.record('default@1', 1.0)
    assert clone.to_state() == book.to_state()


def test_journal_load_missing_or_torn(tmp_path):
    book = league.RatingBook()
    assert not book.load(str(tmp_path / 'absent.json'))
    torn = tmp_path / 'torn.json'
    torn.write_text('{"entries": {tor')
    assert not book.load(str(torn))
    assert book.names() == []                   # fresh book unharmed


# ---------------------------------------------------------------------------
# provisional members: the gateway's external players


def test_provisional_member_rating_flow():
    """``seed_provisional`` creates an unrated outsider at the learner's
    current rating; ``record_between`` moves BOTH sides' Elo but books
    the learner-relative PFSP (games, wins) statistics only on the
    provisional side — a rated member's PFSP curve is never polluted by
    third-party matches — and the promotion denominator never moves."""
    book = league.RatingBook(track_sigma=False, k_factor=32.0)
    book.seed('default@1', 1300.0)
    book.entry(league.LEARNER)['rating'] = 1250.0
    e = book.seed_provisional('gateway:alice')
    assert book.is_provisional('gateway:alice')
    assert e['rating'] == pytest.approx(1250.0)      # learner-seeded
    assert book.seed_provisional('gateway:alice') is e   # idempotent
    assert not book.is_provisional('default@1')
    assert not book.is_provisional('nobody')

    before = book.games_since_promote
    book.record_between('gateway:alice', 'default@1', 1.0)   # upset win
    assert book.rating('gateway:alice') > 1250.0
    assert book.rating('default@1') < 1300.0
    assert book.games('gateway:alice') == 1
    assert book.win_rate('gateway:alice') == pytest.approx(1.0)
    assert book.games('default@1') == 0              # rated side untouched
    assert book.games_since_promote == before        # gate never fed
    # the mirrored loss books on the provisional side as its own score
    book.record_between('default@1', 'gateway:alice', 1.0)
    assert book.games('gateway:alice') == 2
    assert book.win_rate('gateway:alice') == pytest.approx(0.5)


def test_provisional_flag_survives_journal_round_trip(tmp_path):
    path = str(tmp_path / 'ratings.json')
    book = league.RatingBook()
    book.seed_provisional('gateway:bob', rating=1111.0)
    book.record_between('gateway:bob', 'default@1', 0.0)
    book.save(path)
    clone = league.RatingBook()
    assert clone.load(path)
    assert clone.is_provisional('gateway:bob')
    assert not clone.is_provisional('default@1')
    assert clone.rating('gateway:bob') == book.rating('gateway:bob')
    assert clone.to_state() == book.to_state()


def test_provisional_games_never_feed_promotion_gate(tmp_path):
    """Neither ``record_between`` third-party games nor learner games
    against a provisional opponent count toward ``min_games`` — only
    learner-vs-league games can promote a champion."""
    pool, _ = _pool_with_versions(tmp_path, [1, 2], promote_margin=0.0,
                                  min_games=2)
    book = league.RatingBook()
    book.seed_provisional('gateway:bob')
    book.entry(league.LEARNER)['rating'] = 2000.0    # miles past margin
    book.record('gateway:bob', 1.0)                  # learner vs outsider
    book.record_between('gateway:bob', 'default@1', 1.0)
    assert book.games_since_promote == 0
    assert not pool.should_promote(book)             # 0 of 2 gate games
    book.record('default@1', 1.0)
    book.record('random', 1.0)
    book.entry(league.LEARNER)['rating'] = 2000.0
    assert book.games_since_promote == 2
    assert pool.should_promote(book)


# ---------------------------------------------------------------------------
# the promotion gate


def test_should_promote_requires_margin_and_games(tmp_path):
    pool, _ = _pool_with_versions(tmp_path, [1, 2], promote_margin=30.0,
                                  min_games=5)
    book = league.RatingBook()
    book.seed('default@1', 1200.0)
    book.seed(league.LEARNER, 1240.0)           # clears the margin...
    assert not pool.should_promote(book)        # ...but 0 games booked
    for _ in range(5):
        book.record('random', 0.5)
    book.entry(league.LEARNER)['rating'] = 1240.0
    assert pool.should_promote(book)
    book.entry(league.LEARNER)['rating'] = 1229.0   # inside the margin
    assert not pool.should_promote(book)
    pool.champion = None                        # headless line: bootstrap
    assert not pool.should_promote(book)        # promotion is the registry's


class _LeagueStub:
    """The REAL Learner league epoch-sync over a synthetic registry (the
    method needs only args/model_epoch and the league triple)."""

    def __init__(self, args, pool, book, journal, epoch):
        from handyrl_tpu.train import Learner
        self.args = args
        self._registry = None
        self._league = pool
        self._league_ratings = book
        self._league_journal = journal
        self._league_sampled = {}
        self.model_epoch = epoch
        self._registry_root = Learner._registry_root.__get__(self)
        self._ensure_registry = Learner._ensure_registry.__get__(self)
        self._league_epoch_sync = Learner._league_epoch_sync.__get__(self)


def test_epoch_sync_promotes_through_the_gate(tmp_path):
    root = str(tmp_path / 'models')
    os.makedirs(root)
    pool, reg = _pool_with_versions(tmp_path / 'models', [1, 2],
                                    promote_margin=10.0, min_games=3)
    journal = league.journal_path(root)
    book = league.make_rating_book(pool.args)
    stub = _LeagueStub({'model_dir': root, 'serving': {}}, pool, book,
                       journal, epoch=2)

    # learner well above the incumbent but short on games: no flip
    book.entry(league.LEARNER)['rating'] = 1300.0
    book.record('random', 1.0)
    stub._league_epoch_sync()
    assert reg.resolve('default', 'champion')[0] == '1'
    assert book.promotions == 0
    # fresh members were seeded at the learner's rating, not the cold start
    assert book.rating('default@2') == book.rating(league.LEARNER)

    for _ in range(3):
        book.record('random', 0.5)
    book.entry(league.LEARNER)['rating'] = \
        book.rating('default@1') + 10.0         # exactly the margin
    stub._league_epoch_sync()
    assert ModelRegistry(root).resolve('default', 'champion')[0] == '2'
    assert book.promotions == 1
    assert book.games_since_promote == 0
    assert pool.champion == 'default@2'
    # the journal was written atomically and reloads bit-identically
    clone = league.RatingBook()
    assert clone.load(journal)
    assert clone.to_state() == book.to_state()


def test_epoch_sync_refuses_inside_margin(tmp_path):
    root = str(tmp_path / 'models')
    os.makedirs(root)
    pool, reg = _pool_with_versions(tmp_path / 'models', [1, 2],
                                    promote_margin=50.0, min_games=1)
    book = league.make_rating_book(pool.args)
    stub = _LeagueStub({'model_dir': root, 'serving': {}}, pool, book,
                       league.journal_path(root), epoch=2)
    book.record('random', 1.0)
    book.entry(league.LEARNER)['rating'] = book.rating('default@1') + 49.0
    stub._league_epoch_sync()
    assert reg.resolve('default', 'champion')[0] == '1'
    assert book.promotions == 0


# ---------------------------------------------------------------------------
# keep_checkpoints GC pins league members


class _GcLeagueStub:
    def __init__(self, args, pool):
        from handyrl_tpu.train import Learner
        self.args = args
        self._league = pool
        self.model_path = Learner.model_path.__get__(self)
        self._gc_checkpoints = Learner._gc_checkpoints.__get__(self)
        self._registry_root = Learner._registry_root.__get__(self)


def test_gc_pins_league_member_checkpoints(tmp_path):
    from handyrl_tpu import telemetry
    model_dir = str(tmp_path / 'models')
    os.makedirs(model_dir)
    for e in (1, 2, 3, 4, 5):
        checksummed_write_bytes(os.path.join(model_dir, '%d.ckpt' % e),
                                b'ckpt-%d' % e)
    # no registry manifest: the ONLY pin is the league membership
    pool = league.LeaguePool({}, 'default')
    pool._member_paths = {
        'default@1': os.path.join(model_dir, '1.ckpt')}
    stub = _GcLeagueStub({'keep_checkpoints': 2, 'model_dir': model_dir,
                          'eval': {}, 'serving': {}}, pool)
    before = telemetry.counter('guard_ckpt_gc_pinned_total').value
    stub._gc_checkpoints()
    left = sorted(int(n.split('.')[0]) for n in os.listdir(model_dir)
                  if n.endswith('.ckpt'))
    # 4,5 kept by the window; 1 kept by the league pin; 2,3 collected
    assert left == [1, 4, 5]
    assert telemetry.counter('guard_ckpt_gc_pinned_total').value == before + 1
    # membership rotates away: the next pass collects the old member
    pool._member_paths = {}
    stub._gc_checkpoints()
    left = sorted(int(n.split('.')[0]) for n in os.listdir(model_dir)
                  if n.endswith('.ckpt'))
    assert left == [4, 5]


# ---------------------------------------------------------------------------
# ledger re-issue keeps the server-stamped opponent


def test_ledger_reissue_preserves_league_assignment():
    ledger = TaskLedger(deadline=300.0, clock=lambda: 0.0)
    role_args = {'role': 'g', 'player': [0], 'model_id': {0: 7, 1: 3},
                 'sample_key': 41, 'league_opponent': 'default@3',
                 'league_seat': 0}
    original = copy.deepcopy(role_args)
    ledger.assign(('h', 1), role_args)
    assert role_args['task_id'] == 0
    ledger.fail_endpoint(('h', 1))
    reissued = ledger.next_reissue()
    assert reissued == original                 # bit-identical replay
    assert 'task_id' not in reissued
    # rating-match 'e' stamps survive the same way
    e_args = {'role': 'e', 'player': [1], 'model_id': {0: -1, 1: -1},
              'opponent': 'rulebase', 'league_rating_match': True}
    e_orig = copy.deepcopy(e_args)
    ledger.assign(('h', 2), e_args)
    ledger.fail_endpoint(('h', 2))
    assert ledger.next_reissue() == e_orig


# ---------------------------------------------------------------------------
# worker-mode Evaluator: stamped opponents and registry:// specs


def test_evaluator_honors_server_stamped_opponent(tmp_path):
    from handyrl_tpu.evaluation import Evaluator
    env, w = _ttt_wrapper()
    ckpt = tmp_path / 'member.ckpt'
    ckpt.write_bytes(w.params_bytes())
    # the local pool says 'random'; the server-stamped task says the member
    ev = Evaluator(env, {'eval': {'opponent': ['random']}})
    rec = ev.execute({0: w, 1: None},
                     {'role': 'e', 'player': [0], 'opponent': str(ckpt),
                      'league_rating_match': True})
    assert rec is not None
    assert rec['opponent'] == str(ckpt)
    assert abs(sum(rec['result'].values())) < 1e-9
    # without the stamp the pool draw still applies
    rec = ev.execute({0: w, 1: None}, {'role': 'e', 'player': [0]})
    assert rec['opponent'] == 'random'


def test_evaluator_accepts_registry_spec_opponent(tmp_path):
    """eval.opponent entries of the form registry://root/line@sel resolve
    through the registry on the worker-mode (sequential) Evaluator."""
    from handyrl_tpu.evaluation import Evaluator, split_model_specs
    env, w = _ttt_wrapper()
    reg = ModelRegistry(str(tmp_path))
    reg.publish('default', snapshot=w.snapshot(), version=1, promote=True)
    spec = 'registry://%s/default@champion' % tmp_path
    assert split_model_specs(spec) == [spec]
    ev = Evaluator(env, {'eval': {'opponent': [spec]}})
    for seat in (0, 1):
        rec = ev.execute({seat: w, 1 - seat: None},
                         {'role': 'e', 'player': [seat]})
        assert rec is not None
        assert rec['opponent'] == spec
        assert abs(sum(rec['result'].values())) < 1e-9
    assert len(ev._opponent_cache) == 1         # resolved once, reused


# ---------------------------------------------------------------------------
# config surface


def test_config_league_block_validation():
    ok = apply_defaults({'env_args': {'env': 'TicTacToe'},
                         'train_args': {'league': {'enabled': True},
                                        'serving': {'publish': True}}})
    assert ok['train_args']['league']['curve'] == 'variance'
    with pytest.raises(AssertionError):         # league needs the registry
        apply_defaults({'env_args': {'env': 'TicTacToe'},
                        'train_args': {'league': {'enabled': True}}})
    with pytest.raises(AssertionError):
        apply_defaults({'env_args': {'env': 'TicTacToe'},
                        'train_args': {'league': {'curve': 'sideways'}}})
    with pytest.raises(AssertionError):
        apply_defaults({'env_args': {'env': 'TicTacToe'},
                        'train_args': {'league': {'anchors': ['lizard']}}})


# ---------------------------------------------------------------------------
# ConnectX: the league's fourth environment


def test_connectx_rule_based_tactics():
    env = make_env({'env': 'ConnectX'})
    env.reset()
    # O threatens a horizontal four at columns 0-3 -> win now at 3
    for col in (0, 6, 1, 6, 2, 5):
        env.play(col)
    assert env.rule_based_action(env.turn()) == 3
    env.play(3)
    assert env.terminal() and env.outcome()[0] == 1.0

    env.reset()
    # X must block O's open three (columns 0-2) at column 3
    for col in (0, 6, 1, 6, 2):
        env.play(col)
    assert env.rule_based_action(env.turn()) == 3


def test_connectx_net_and_league_config():
    env = make_env({'env': 'ConnectX'})
    env.reset()
    w = ModelWrapper(env.net())
    obs = env.observation(0)
    assert obs.shape == (3, 6, 7)
    out = w.inference(obs, None)
    assert out['policy'].shape == (7,)
    assert -1.0 <= float(out['value'][0]) <= 1.0
    # a league config over ConnectX validates end to end
    args = apply_defaults({'env_args': {'env': 'ConnectX'},
                           'train_args': {'league': {'enabled': True},
                                          'serving': {'publish': True}}})
    assert args['train_args']['league']['enabled']


# ---------------------------------------------------------------------------
# the fleet e2e: PFSP draws, restart-safe ratings, promotion in the manifest


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 8,
                          'forward_steps': 8, 'num_batchers': 1,
                          'eval_rate': 0.3, 'seed': 11,
                          'restart_epoch': -1, 'keep_checkpoints': 3,
                          'metrics_jsonl': %(metrics)r,
                          'model_dir': %(model_dir)r,
                          'serving': {'publish': True, 'line': 'default'},
                          'league': {'enabled': True, 'self_play_rate': 0.0,
                                     'rating_match_rate': 1.0,
                                     'curve': 'uniform', 'min_games': 1,
                                     'promote_margin': 0.0}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def _spawn(path, env, log):
    return subprocess.Popen([sys.executable, str(path)], env=env,
                            stdout=log, stderr=subprocess.STDOUT)


def _stop(proc, timeout=30):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_league_fleet_restart_preserves_ratings_and_promotes(tmp_path):
    model_dir = str(tmp_path / 'models')
    metrics = str(tmp_path / 'metrics.jsonl')
    journal = os.path.join(model_dir, 'league_ratings.json')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {'model_dir': model_dir,
                                            'metrics': metrics})
    worker_py.write_text(WORKER_SCRIPT)
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}

    # -- phase 1: run until a few epochs published, then SIGTERM ----------
    l1_log = open(tmp_path / 'learner1.log', 'w')
    w1_log = open(tmp_path / 'worker1.log', 'w')
    learner = _spawn(learner_py, env, l1_log)
    worker = None
    try:
        time.sleep(3)
        worker = _spawn(worker_py, env, w1_log)
        deadline = time.time() + 240
        target = os.path.join(model_dir, '3.ckpt')
        while time.time() < deadline:
            if os.path.exists(target) or learner.poll() is not None:
                break
            time.sleep(2)
        assert os.path.exists(target), 'phase 1 never reached epoch 3'
    finally:
        _stop(learner)
        if worker is not None:
            _stop(worker)

    assert os.path.exists(journal), 'no ratings journal after phase 1'
    j1_raw = open(journal, 'rb').read()
    j1 = json.loads(j1_raw)
    assert j1['entries'], 'phase 1 booked no rated games'

    # the production journal round-trips through the book bit-identically
    book = league.RatingBook()
    assert book.load(journal)
    book.save(str(tmp_path / 'roundtrip.json'))
    assert open(str(tmp_path / 'roundtrip.json'), 'rb').read() == j1_raw

    # -- phase 2: restart (auto-resume) and run to completion -------------
    l2_log = open(tmp_path / 'learner2.log', 'w')
    w2_log = open(tmp_path / 'worker2.log', 'w')
    learner = _spawn(learner_py, env, l2_log)
    worker = None
    try:
        time.sleep(3)
        worker = _spawn(worker_py, env, w2_log)
        deadline = time.time() + 240
        while time.time() < deadline:
            if learner.poll() is not None:
                break
            time.sleep(2)
    finally:
        _stop(worker if worker is not None else learner)
        _stop(learner)

    log2 = open(tmp_path / 'learner2.log').read()
    assert 'league: reloaded ratings journal' in log2, \
        'restart did not reload the ratings book'

    j2 = json.loads(open(journal, 'rb').read())
    # ratings survived the restart: nothing booked in phase 1 was lost
    assert set(j1['entries']) <= set(j2['entries'])
    for name, entry in j1['entries'].items():
        assert j2['entries'][name]['games'] >= entry['games']
    assert j2['promotions'] >= max(1, j1['promotions'])

    # the metrics stream shows PFSP drawing >= 2 distinct registry versions
    sampled = set()
    league_recs = 0
    with open(metrics) as f:
        for line in f:
            rec = json.loads(line)
            lg = rec.get('league')
            if not lg:
                continue
            league_recs += 1
            sampled.update(lg.get('opponents_sampled') or {})
            assert 'ratings' in lg and 'champion' in lg
    assert league_recs > 0, 'no league blocks in metrics_jsonl'
    versions = {m for m in sampled if '@' in m}
    assert len(versions) >= 2, \
        'PFSP sampled %r: wanted >= 2 registry versions' % (sampled,)

    # the rating-gated promotion landed in the registry manifest
    reg = ModelRegistry(model_dir)
    champ, meta = reg.resolve('default', 'champion')
    assert int(champ) >= 1 and meta['path']
    # every live member checkpoint survived retention GC (keep=3 < members)
    pool = league.LeaguePool({}, 'default')
    pool.refresh(reg)
    for path in pool.member_paths():
        assert os.path.exists(path), 'league member %s collected' % path
