"""CLI end to end: `python main.py --train` then `--eval` as real processes
with a user-style config.yaml."""

import os
import subprocess
import sys

import pytest

CONFIG = """
env_args:
    env: 'TicTacToe'

train_args:
    batch_size: 8
    forward_steps: 8
    update_episodes: 15
    minimum_episodes: 15
    epochs: 1
    generation_envs: 8
    num_batchers: 1
"""


@pytest.mark.timeout(600)
def test_cli_train_then_eval(tmp_path):
    (tmp_path / 'config.yaml').write_text(CONFIG)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'PYTHONPATH': repo + os.pathsep + os.environ.get('PYTHONPATH', '')}

    train = subprocess.run(
        [sys.executable, os.path.join(repo, 'main.py'), '--train'],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=300)
    assert train.returncode == 0, train.stdout[-2000:] + train.stderr[-2000:]
    assert 'updated model(' in train.stdout
    assert (tmp_path / 'models' / 'latest.ckpt').exists()

    ev = subprocess.run(
        [sys.executable, os.path.join(repo, 'main.py'), '--eval',
         'models/latest.ckpt', '4', '1'],
        cwd=tmp_path, env=env, capture_output=True, text=True, timeout=240)
    assert ev.returncode == 0, ev.stdout[-2000:] + ev.stderr[-2000:]
    assert 'total games = 4' in ev.stdout
    assert '---agent 0---' in ev.stdout
