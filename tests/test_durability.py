"""Durable training plane: framed WAL records, the episode spool, the
task-ledger journal, and the full learner-restart end-to-end (SIGKILL the
learner mid-run; the restarted process recovers spooled episodes, re-issues
the persisted book, and the surviving gathers reattach without respawning).

The in-memory ledger semantics (assign/admit/reap) are pinned in
tests/test_fault_tolerance.py; this file covers what survives a dead
process.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from handyrl_tpu.fault import RESTORED_ENDPOINT, LedgerJournal, TaskLedger
from handyrl_tpu.utils.fs import (append_framed_record, frame_record,
                                  open_append, read_framed_records)


# ---------------------------------------------------------------------------
# framed records (utils/fs.py)


def _write_frames(path, payloads):
    fd = open_append(str(path))
    try:
        for payload in payloads:
            append_framed_record(fd, payload)
    finally:
        os.close(fd)


def test_framed_record_roundtrip(tmp_path):
    path = tmp_path / 'frames.wal'
    payloads = [b'alpha', b'', b'x' * 4096]
    _write_frames(path, payloads)
    records, valid_bytes, torn = read_framed_records(str(path))
    assert records == payloads
    assert valid_bytes == path.stat().st_size
    assert not torn


def test_framed_record_torn_tail_is_detected_and_truncatable(tmp_path):
    path = tmp_path / 'frames.wal'
    _write_frames(path, [b'good-1', b'good-2'])
    keep = path.stat().st_size
    # a torn final record: header + only half the payload made it to disk
    with open(path, 'ab') as f:
        f.write(frame_record(b'torn-record-payload')[:-7])
    records, valid_bytes, torn = read_framed_records(str(path))
    assert records == [b'good-1', b'good-2']
    assert valid_bytes == keep
    assert torn
    os.truncate(str(path), valid_bytes)
    assert read_framed_records(str(path)) == ([b'good-1', b'good-2'],
                                              keep, False)


def test_framed_record_crc_mismatch_stops_the_scan(tmp_path):
    path = tmp_path / 'frames.wal'
    _write_frames(path, [b'aaaa', b'bbbb', b'cccc'])
    data = bytearray(path.read_bytes())
    # flip a payload byte of the SECOND record: everything from there on
    # is untrusted (WAL semantics: no resynchronization past corruption)
    frame_len = len(frame_record(b'aaaa'))
    data[2 * frame_len - 1] ^= 0xFF   # last payload byte of record 2
    path.write_bytes(bytes(data))
    records, valid_bytes, torn = read_framed_records(str(path))
    assert records == [b'aaaa']
    assert valid_bytes == len(frame_record(b'aaaa'))
    assert torn


# ---------------------------------------------------------------------------
# episode spool


def _make_spool(tmp_path, **kw):
    from handyrl_tpu.spool import EpisodeSpool
    kw.setdefault('segment_mb', 64.0)
    kw.setdefault('keep_segments', 2)
    return EpisodeSpool(str(tmp_path), **kw)


def test_spool_append_recover_roundtrip(tmp_path):
    from handyrl_tpu.connection import pack, unpack
    spool = _make_spool(tmp_path)
    for idx in range(5):
        spool.append(idx, pack({'idx': idx, 'episode': {'n': idx}}))
    spool.close()

    fresh = _make_spool(tmp_path)
    recovered = fresh.recover(2, unpack)
    assert [rec['idx'] for rec in recovered] == [2, 3, 4]
    assert [rec['episode']['n'] for rec in recovered] == [2, 3, 4]
    # horizon past everything -> nothing to replay
    assert _make_spool(tmp_path).recover(5, unpack) == []


def test_spool_truncates_torn_tail_on_recover(tmp_path):
    from handyrl_tpu.connection import pack, unpack
    spool = _make_spool(tmp_path)
    for idx in range(3):
        spool.append(idx, pack({'idx': idx, 'episode': idx}))
    spool.close()
    (segment,) = [os.path.join(spool.root, n)
                  for n in os.listdir(spool.root)]
    good_size = os.path.getsize(segment)
    with open(segment, 'ab') as f:
        f.write(frame_record(pack({'idx': 3, 'episode': 3}))[:-3])

    recovered = _make_spool(tmp_path).recover(0, unpack)
    assert [rec['idx'] for rec in recovered] == [0, 1, 2]
    assert os.path.getsize(segment) == good_size   # tail truncated in place


def test_spool_rotation_gc_and_restart_sequencing(tmp_path):
    from handyrl_tpu.connection import pack, unpack
    # ~1KB segments: every append rotates, so each record is its own file
    spool = _make_spool(tmp_path, segment_mb=0.0001, keep_segments=1)
    for idx in range(6):
        spool.append(idx, pack({'idx': idx, 'episode': 'x' * 256}))
    segments = sorted(os.listdir(spool.root))
    assert len(segments) == 6

    # horizon 4: segments holding idx 0..3 are eligible, the newest ONE of
    # them is kept as cushion (keep_segments=1) -> 3 removed
    assert spool.gc(4) == 3
    assert len(sorted(os.listdir(spool.root))) == 3
    # the survivors still replay everything past the horizon
    recovered = _make_spool(tmp_path, keep_segments=1).recover(4, unpack)
    assert [rec['idx'] for rec in recovered] == [4, 5]
    spool.close()

    # a restarted spool appends into a FRESH segment numbered past every
    # survivor — two generations never interleave within one file
    fresh = _make_spool(tmp_path, segment_mb=0.0001, keep_segments=1)
    fresh.recover(6, unpack)
    fresh.append(6, pack({'idx': 6, 'episode': 'y'}))
    fresh.close()
    newest = sorted(os.listdir(fresh.root))[-1]
    assert newest > sorted(os.listdir(fresh.root))[-2]


# ---------------------------------------------------------------------------
# ledger journal: snapshot + delta persistence


class _Clock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_ledger_journal_roundtrip_preserves_payloads(tmp_path):
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    ledger.journal = LedgerJournal(str(tmp_path))
    # int-keyed model_id is the regression trap: a JSON journal would
    # stringify the keys and break the byte-identical re-issue contract
    t0 = {'role': 'g', 'model_id': {0: 5, 1: 3}, 'sample_key': 17}
    t1 = {'role': 'e', 'model_id': {0: 5}, 'sample_key': 4}
    t2 = {'role': 'g', 'model_id': {0: 5}, 'sample_key': 18}
    tid0 = ledger.assign('ep-a', t0)
    ledger.assign('ep-a', t1)
    ledger.assign('ep-b', t2)
    ledger.admit([{'args': {'task_id': tid0}}])
    ledger.flush_journal()
    ledger.journal.close()

    state = LedgerJournal(str(tmp_path)).load()
    assert state['next_tid'] == 3
    assert sorted(state['tasks']) == [1, 2]
    assert state['tasks'][1] == {'role': 'e', 'model_id': {0: 5},
                                 'sample_key': 4}
    assert state['tasks'][2]['model_id'] == {0: 5}

    # restore into a fresh book: the outstanding tasks re-issue with their
    # ORIGINAL payloads, ahead of fresh work, exactly once
    restored = TaskLedger(deadline=30.0, clock=_Clock())
    restored.restore_state(state)
    assert restored.outstanding() == 2
    assert restored.outstanding_by_endpoint() == {RESTORED_ENDPOINT: 2}
    first, second = restored.next_reissue(), restored.next_reissue()
    assert {first['sample_key'], second['sample_key']} == {4, 18}
    assert restored.next_reissue() is None
    # a fresh assignment must not collide with a restored task_id
    assert restored.assign('ep-new', {'role': 'g', 'model_id': {}}) == 3


def test_ledger_journal_snapshot_folds_deltas_and_replays_idempotently(
        tmp_path):
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    ledger.journal = LedgerJournal(str(tmp_path))
    ledger.assign('ep', {'role': 'g', 'sample_key': 0})
    tid1 = ledger.assign('ep', {'role': 'g', 'sample_key': 1})
    ledger.admit([{'args': {'task_id': tid1}}])
    ledger.flush_journal()
    # epoch sync: snap the book, truncate the delta journal
    ledger.journal.snapshot(ledger.snapshot_state())
    assert os.path.getsize(os.path.join(str(tmp_path),
                                        LedgerJournal.DELTA)) == 0
    # post-snapshot churn journals as fresh deltas
    ledger.assign('ep', {'role': 'g', 'sample_key': 2})
    ledger.journal.close()

    state = LedgerJournal(str(tmp_path)).load()
    assert sorted(state['tasks']) == [0, 2]
    assert state['next_tid'] == 3
    # replay tolerates ops against tids the snapshot already folded in:
    # 'c'/'x'/'s' on an unknown tid are no-ops, not corruption
    journal = LedgerJournal(str(tmp_path))
    journal.record('c', tid1)
    journal.record('s', 99)
    journal.close()
    again = LedgerJournal(str(tmp_path)).load()
    assert sorted(again['tasks']) == [0, 2]
    assert again['reissue'] == state['reissue']


def test_ledger_journal_torn_delta_tail_truncates_on_load(tmp_path):
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    ledger.journal = LedgerJournal(str(tmp_path))
    ledger.assign('ep', {'role': 'g', 'sample_key': 7})
    ledger.journal.close()
    delta = os.path.join(str(tmp_path), LedgerJournal.DELTA)
    good_size = os.path.getsize(delta)
    with open(delta, 'ab') as f:
        f.write(b'HRLW\x00\x00\xff\xff')   # header promising absent bytes

    state = LedgerJournal(str(tmp_path)).load()
    assert sorted(state['tasks']) == [0]
    assert state['tasks'][0]['sample_key'] == 7
    assert os.path.getsize(delta) == good_size


def test_restored_task_cancel_closes_the_unflushed_completion_window(
        tmp_path):
    """The one crash window: an episode was admitted (it reached the spool)
    but its 'c' record never flushed. On restart the spool recovery cancels
    the task straight out of the restored state, so it neither re-issues
    nor double-counts — and a reattached gather's replayed upload for a
    cancelled tid drops as an ordinary duplicate."""
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    ledger.journal = LedgerJournal(str(tmp_path))
    spooled = {'role': 'g', 'sample_key': 5}
    lost = {'role': 'g', 'sample_key': 6}
    tid_spooled = ledger.assign('ep', spooled)
    ledger.assign('ep', lost)
    ledger.admit([{'args': {'task_id': tid_spooled}}])
    # crash here: the completion was never flushed to the journal
    ledger.journal.close()

    state = LedgerJournal(str(tmp_path)).load()
    assert sorted(state['tasks']) == [0, 1]
    # spool recovery: the recovered episode's task_id cancels its book entry
    state['tasks'].pop(tid_spooled, None)
    restored = TaskLedger(deadline=30.0, clock=_Clock())
    restored.restore_state(state)
    reissued = restored.next_reissue()
    assert reissued == {'role': 'g', 'sample_key': 6}   # lost, sans task_id
    assert restored.next_reissue() is None
    # the replayed upload for the spooled episode is a duplicate, not a count
    assert restored.admit([{'args': {'task_id': tid_spooled}}]) == []
    assert restored.stats['duplicates'] == 1


def test_restored_reissue_skips_tasks_a_reattached_gather_completed():
    ledger = TaskLedger(deadline=30.0, clock=_Clock())
    state = {'tasks': {0: {'role': 'g', 'sample_key': 0},
                       1: {'role': 'g', 'sample_key': 1}},
             'reissue': [], 'next_tid': 2}
    ledger.restore_state(state)
    # a surviving gather replays its resend buffer BEFORE the next 'args'
    # request drains the restored queue: task 0 completes normally
    assert len(ledger.admit([{'args': {'task_id': 0}}])) == 1
    assert ledger.next_reissue() == {'role': 'g', 'sample_key': 1}
    assert ledger.next_reissue() is None   # 0 must not re-issue


# ---------------------------------------------------------------------------
# config validation


def test_durability_config_validation():
    from handyrl_tpu.config import apply_defaults
    args = apply_defaults({})
    dur = args['train_args']['durability']
    assert dur['spool'] is True and dur['ledger_snapshot'] is True
    with pytest.raises(AssertionError):
        apply_defaults({'train_args': {'durability': {'segment_mb': 0}}})
    with pytest.raises(AssertionError):
        apply_defaults({'train_args': {'durability': {'keep_segments': -1}}})
    with pytest.raises(AssertionError):
        apply_defaults({'train_args': {'league': {
            'rating_flush_seconds': -1}}})


# ---------------------------------------------------------------------------
# learner-restart end-to-end: SIGKILL the learner, restart it, and require
# the durable plane to hand back every admitted episode + in-flight task
# while the surviving gathers reattach in place


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax, json
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 3,
                          'forward_steps': 8, 'num_batchers': 1,
                          'restart_epoch': -1,
                          'model_dir': %(model_dir)r,
                          'fault_tolerance': {
                              'heartbeat_interval': 1.0,
                              'liveness_timeout': 8.0,
                              'rpc_timeout': 30.0,
                              'task_deadline': 30.0,
                              'reconnect_initial_delay': 0.25,
                              'reconnect_max_delay': 1.0,
                              'reconnect_max_tries': 240}}}
    args = apply_defaults(raw)
    learner = Learner(args=args, remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, learner.num_episodes,
          learner.num_returned_episodes, flush=True)
    print('LEDGER', json.dumps(learner.ledger.stats), flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


def _wait_for(predicate, deadline, poll=0.5):
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_learner_restart_zero_loss(tmp_path):
    """SIGKILL the learner mid-run, restart it with ``restart_epoch: -1``:
    the restarted process must adopt the run token, restore the ledger
    book, and finish the full epoch budget while the ORIGINAL worker-host
    gathers reattach through the resume handshake — zero gather respawns."""
    entry_port, data_port = 21930, 21931
    model_dir = str(tmp_path / 'models')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {'model_dir': model_dir})
    worker_py.write_text(WORKER_SCRIPT)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
                'HANDYRL_TPU_DATA_PORT': str(data_port),
                'PYTHONPATH': repo + os.pathsep
                + os.environ.get('PYTHONPATH', '')}

    log1 = open(tmp_path / 'learner1.log', 'w')
    log2 = open(tmp_path / 'learner2.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner2 = worker = None
    learner1 = subprocess.Popen([sys.executable, str(learner_py)],
                                env=base_env, stdout=log1,
                                stderr=subprocess.STDOUT)
    try:
        time.sleep(3)
        worker = subprocess.Popen([sys.executable, str(worker_py)],
                                  env=base_env, stdout=worker_log,
                                  stderr=subprocess.STDOUT)

        def says(path, needle):
            return needle in (tmp_path / path).read_text()

        # let the run get past warmup (the fleet is generating and the
        # ledger book is live), then murder the learner outright
        assert _wait_for(lambda: says('learner1.log', 'started training')
                         or learner1.poll() is not None,
                         time.time() + 240), 'fleet never reached warmup'
        assert learner1.poll() is None, 'learner died before the kill'
        time.sleep(2)   # a little mid-epoch churn: in-flight tasks + spool
        learner1.send_signal(signal.SIGKILL)
        learner1.wait(timeout=30)

        learner2 = subprocess.Popen([sys.executable, str(learner_py)],
                                    env=base_env, stdout=log2,
                                    stderr=subprocess.STDOUT)

        def done():
            return (says('learner2.log', 'LEARNER DONE')
                    or learner2.poll() is not None)
        assert _wait_for(done, time.time() + 300), \
            'restarted learner hung'
        learner2.wait(timeout=120)
        worker.wait(timeout=120)
    finally:
        for proc in (worker, learner2, learner1):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
        log1.close()
        log2.close()
        worker_log.close()

    out2 = (tmp_path / 'learner2.log').read_text()
    worker_out = (tmp_path / 'worker.log').read_text()

    # the durable plane actually engaged on restart
    assert 'durable plane: restored ledger book' in out2
    # the surviving gathers rode through: resume handshake, no respawn
    assert 'reattached across a learner restart' in worker_out
    assert 'respawning' not in worker_out, \
        'a gather respawned — the fleet did not survive the restart'
    # the full budget completed with converged accounting
    done_line = [l for l in out2.splitlines()
                 if l.startswith('LEARNER DONE')][0]
    _, _, epoch, _num_episodes, num_returned = done_line.split()
    assert int(epoch) == 3
    assert int(num_returned) >= 36
    ledger = json.loads(out2.split('LEDGER', 1)[1].strip().splitlines()[0])
    assert ledger['completed'] <= ledger['assigned'] + ledger['reissued']
