"""Ring attention vs full attention on the 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu.parallel.mesh import make_mesh
from handyrl_tpu.parallel.ring_attention import full_attention, ring_attention


@pytest.mark.parametrize('T', [16, 64])
def test_ring_matches_full_attention(T):
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    rng = np.random.RandomState(0)
    B, H, D = 2, 4, 16
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))

    want = full_attention(q, k, v)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_jits_under_mesh():
    mesh = make_mesh()
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    out = fn(q, q, q)
    assert out.shape == (1, 32, 2, 8)
    assert np.all(np.isfinite(np.asarray(out)))
