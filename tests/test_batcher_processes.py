"""Process-based batch building (batcher_processes=True) end to end."""

import pytest

from handyrl_tpu.config import apply_defaults
from handyrl_tpu.train import Learner


@pytest.mark.timeout(600)
def test_learner_with_process_batchers(tmp_path):
    raw = {
        'env_args': {'env': 'TicTacToe'},
        'train_args': {
            'batch_size': 16, 'update_episodes': 25, 'minimum_episodes': 30,
            'epochs': 1, 'generation_envs': 8, 'forward_steps': 8,
            'num_batchers': 2, 'batcher_processes': True,
            'model_dir': str(tmp_path / 'models'),
        },
    }
    learner = Learner(args=apply_defaults(raw))
    learner.run()
    assert learner.model_epoch == 1
    assert (tmp_path / 'models' / '1.ckpt').exists()
