"""Hungry Geese conformance fixtures: the nasty rules, pinned.

Each fixture encodes one behavior of the canonical kaggle interpreter per
the resolution order documented in docs/geese_rules.md, checked against
BOTH engines (host simulator and jax twin), plus a long differential fuzz
keeping the two engines in lockstep.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from handyrl_tpu.envs import jax_hungry_geese as jhg
from handyrl_tpu.envs.kaggle.hungry_geese import Environment as HostGeese

from test_jax_geese import _host_with, _manual_state

# board refresher: cells are row*11 + col on a 7x11 torus;
# actions 0=NORTH(-row) 1=SOUTH(+row) 2=WEST(-col) 3=EAST(+col)
N, S, W, E = 0, 1, 2, 3


def _both(geese, food, actions, last_actions=None, steps=0):
    """Step both engines on the same position; return (host, dev_state)."""
    host = _host_with(geese, food, last_actions=last_actions, steps=steps)
    host.step(dict(actions))
    dev = _manual_state(geese, food, last_actions=last_actions, steps=steps)
    dev2 = jhg.step(dev, jnp.asarray([[actions[p] for p in range(4)]]))
    return host, dev2


def _alive(host, dev):
    return list(host.alive), list(np.asarray(dev.alive)[0])


def test_reversal_kills_even_at_length_1():
    """Canonical 'Opposite action' has NO length guard (docs/geese_rules.md
    step 1): a length-1 goose attempting its opposite action dies."""
    geese = [[5], [20], [40], [60]]
    host, dev = _both(geese, [70, 75], {0: W, 1: E, 2: E, 3: E},
                      last_actions={0: E})
    ha, da = _alive(host, dev)
    assert ha == da == [False, True, True, True]


def test_reversal_kills_at_length_2():
    geese = [[5, 4], [20], [40], [60]]
    host, dev = _both(geese, [70, 75], {0: W, 1: E, 2: E, 3: E},
                      last_actions={0: E})
    ha, da = _alive(host, dev)
    assert ha == da == [False, True, True, True]


def test_non_opposite_first_step_is_free():
    """With no last action recorded, any move is legal."""
    geese = [[5], [20], [40], [60]]
    host, dev = _both(geese, [70, 75], {0: W, 1: E, 2: E, 3: E})
    ha, da = _alive(host, dev)
    assert ha == da == [True, True, True, True]


def test_head_swap_length_1_passes_through():
    """Two length-1 geese swapping cells survive: the cross pass only sees
    post-move positions, which no longer overlap (known canonical quirk)."""
    geese = [[0], [1], [40], [60]]
    host, dev = _both(geese, [70, 75], {0: E, 1: W, 2: E, 3: E})
    ha, da = _alive(host, dev)
    assert ha == da == [True, True, True, True]
    assert host.geese[0] == [1] and host.geese[1] == [0]


def test_head_swap_length_2_kills_both():
    """At length >=2 each head lands on the other's post-move neck."""
    geese = [[5, 4], [6, 7], [40], [60]]
    host, dev = _both(geese, [70, 75], {0: E, 1: W, 2: E, 3: E},
                      last_actions={0: E, 1: W})
    ha, da = _alive(host, dev)
    assert ha == da == [False, False, True, True]


def test_two_heads_same_cell_kill_both():
    geese = [[4], [6], [40], [60]]
    host, dev = _both(geese, [70, 75], {0: E, 1: W, 2: E, 3: E})
    ha, da = _alive(host, dev)
    assert ha == da == [False, False, True, True]


def test_eat_then_hunger_same_step_nets_zero():
    """Eat keeps the tail (step 3), hunger pops it (step 6): length
    unchanged on a hunger step that eats."""
    geese = [[5, 4], [30, 31], [50, 51], [60, 61]]
    host, dev = _both(geese, [6, 75], {0: E, 1: W, 2: N, 3: N},
                      steps=jhg.HUNGER_RATE - 1)
    assert host.alive[0] and len(host.geese[0]) == 2
    assert np.asarray(dev.length)[0, 0] == 2
    # the non-eater shrank to 1
    assert host.alive[1] and len(host.geese[1]) == 1
    assert np.asarray(dev.length)[0, 1] == 1


def test_hunger_starves_length_1_goose():
    geese = [[5], [30, 31], [50, 51], [60, 61]]
    host, dev = _both(geese, [70, 75], {0: E, 1: W, 2: N, 3: N},
                      steps=jhg.HUNGER_RATE - 1)
    ha, da = _alive(host, dev)
    assert ha == da == [False, True, True, True]


def test_own_vacated_tail_is_safe_but_eating_onto_tail_kills():
    """Step 4 checks the head against the goose AFTER the tail pop: a
    square loop onto the just-vacated tail is safe; the same move while
    eating keeps the tail and dies."""
    # goose 0: head 1, body 12, 13, tail 2; moving N from 1... build a
    # 2x2 loop: cells 0,1,12,11; head at 0 came from 11 (action N),
    # moving E->1? Simpler: head 11, body 12, 1, tail 0; action N moves
    # head 11 -> 0 (torus up from row1 col0 to row0 col0) onto own tail.
    loop = [11, 12, 1, 0]
    host, dev = _both([list(loop), [40], [50], [60]], [70, 75],
                      {0: N, 1: E, 2: E, 3: E}, last_actions={0: W})
    ha, da = _alive(host, dev)
    assert ha == da == [True, True, True, True]
    assert host.geese[0][0] == 0
    # same geometry, but food on the tail cell: tail kept -> death
    host, dev = _both([list(loop), [40], [50], [60]], [0, 75],
                      {0: N, 1: E, 2: E, 3: E}, last_actions={0: W})
    ha, da = _alive(host, dev)
    assert ha == da == [False, True, True, True]


def test_opponents_vacated_tail_is_safe():
    """A head may enter the cell an opponent's tail left this step."""
    geese = [[8], [5, 6, 7], [40], [60]]     # goose 1 tail at 7, moving W
    host, dev = _both(geese, [70, 75], {0: W, 1: W, 2: E, 3: E},
                      last_actions={1: W})
    ha, da = _alive(host, dev)
    assert ha == da == [True, True, True, True]
    assert host.geese[0] == [7]


def test_self_collided_goose_body_does_not_kill_others():
    """Canonical ordering fixture: a goose removed by self-collision in the
    per-agent phase contributes NOTHING to the cross pass, so another head
    entering its (former) body the same step survives."""
    # goose 1: moving S from head 17 onto its own body cell 28 (NOT the
    # tail, which pops safely) -> self-collision death.
    goose1 = [17, 28, 29, 30, 19, 18]        # head 17; 28 is body, 18 tail
    # goose 0 at 40 moves N into 29 — a cell of goose 1's former body
    geese = [[40], list(goose1), [50], [60]]
    host, dev = _both(geese, [70, 75], {0: N, 1: S, 2: E, 3: E},
                      last_actions={1: W})
    ha, da = _alive(host, dev)
    assert ha == da == [True, False, True, True]


def test_reversed_goose_body_does_not_kill_others():
    """Same ordering property for reversal deaths."""
    goose1 = [20, 21, 22, 23]
    geese = [[31], list(goose1), [50], [60]]  # goose 0 at 31 moves N to 20
    host, dev = _both(geese, [70, 75], {0: N, 1: E, 2: E, 3: E},
                      last_actions={1: W})    # E is opposite of W: reversal
    ha, da = _alive(host, dev)
    assert ha == da == [True, False, True, True]
    assert host.geese[0] == [20]


def test_food_respawn_excludes_occupied_cells():
    """After eating, food is replenished to N_FOOD on cells free of geese
    and other food (host engine; device twin covered by the fuzz below)."""
    rng_seen = set()
    for seed in range(20):
        host = HostGeese({'id': seed})
        geese = [[5, 4], [30, 31], [50], [60]]
        host.geese = [list(g) for g in geese]
        host.prev_geese = [list(g) for g in geese]
        host.food = [6, 75]
        host.alive = [True] * 4
        host.last_actions = {}
        host.step_count = 0
        host.scores = [0.0] * 4
        host._update_scores()
        host.step({0: E, 1: W, 2: N, 3: N})   # goose 0 eats cell 6
        assert len(host.food) == 2
        occupied = {c for g in host.geese for c in g}
        for f in host.food:
            assert f not in occupied
        assert len(set(host.food)) == 2
        rng_seen.add(tuple(sorted(host.food)))
    assert len(rng_seen) > 1                   # spawn is actually random


def test_three_heads_one_cell_kill_all():
    """A >=3-goose pileup on one cell kills every entrant (the pairwise
    head-collision rule has no tie-breaking by length here: all die)."""
    geese = [[5], [27], [15], [60]]           # 5 S, 27 N, 15 E -> cell 16
    host, dev = _both(geese, [70, 75], {0: S, 1: N, 2: E, 3: E})
    ha, da = _alive(host, dev)
    assert ha == da == [False, False, False, True]


def test_pileup_on_food_consumes_and_respawns():
    """Food under a fatal pileup is still eaten (the eat phase precedes the
    collision phase), so it respawns — cell 16 itself is free again after
    the deaths and is a legal respawn target, so assert the count + the
    not-on-occupied-cells invariant, not the respawn location."""
    geese = [[5], [27], [15], [60]]
    host, dev = _both(geese, [16, 75], {0: S, 1: N, 2: E, 3: E})
    ha, da = _alive(host, dev)
    assert ha == da == [False, False, False, True]
    occupied = {c for g in host.geese for c in g}
    assert len(set(host.food)) == 2 and not (set(host.food) & occupied)
    df = np.asarray(dev.food)[0]
    assert len(set(df)) == 2 and 61 not in df   # 61 = survivor's new head


def test_four_way_pileup_ends_the_episode():
    geese = [[5], [27], [15], [17]]
    host, dev = _both(geese, [70, 75], {0: S, 1: N, 2: E, 3: W})
    ha, da = _alive(host, dev)
    assert ha == da == [False, False, False, False]
    assert host.terminal()


def test_outcome_ranks_survival_over_length():
    """Survival steps dominate length in the pairwise-rank outcome."""
    host = _host_with([[5], [30, 31, 32], [50], [60]], [70, 75])
    # kill goose 0 by reversal at step 1; others live to terminal
    host.last_actions = {0: E}
    host.step({0: W, 1: E, 2: E, 3: E})
    assert not host.alive[0]
    out = host.outcome()
    assert out[0] == -1.0                      # died first: beaten by all


@pytest.mark.parametrize('seed', [0, 1])
def test_differential_fuzz_host_vs_jax(seed):
    """>=10k single-goose-steps of random play: the two engines agree on
    alive flags, lengths, goose cells, and food multiset at every step of
    every episode (fresh episodes re-seeded from the host layout)."""
    rng = np.random.RandomState(100 + seed)
    step_fn = jax.jit(jhg.step)     # eager per-step dispatch is ~100x slower
    total_steps = 0
    episodes = 0
    while total_steps < 2600:                  # x4 geese >= 10.4k steps
        host = HostGeese({'id': int(rng.randint(1 << 30))})
        dev = _manual_state([list(g) for g in host.geese], list(host.food))
        episodes += 1
        while not host.terminal():
            acts = {p: int(rng.randint(4)) for p in host.turns()}
            dev_acts = [[acts.get(p, 0) for p in range(4)]]
            pre_food = set(host.food)
            pre_len = [len(g) for g in host.geese]
            hunger = (host.step_count + 1) % jhg.HUNGER_RATE == 0
            host.step(dict(acts))
            dev = step_fn(dev, jnp.asarray(dev_acts, jnp.int32))
            # length-delta law, checked EVERY step: a survivor's length is
            # pre + ate - hunger_pop (eat keeps the tail, the 40th-step
            # hunger pops one; simultaneously they cancel). This pins the
            # hunger boundary and the eat+starve interaction at every
            # random position the fuzz reaches, not just the fixtures.
            for p in range(4):
                if host.alive[p]:
                    ate = int(host.geese[p][0] in pre_food)
                    assert len(host.geese[p]) == \
                        pre_len[p] + ate - int(hunger), \
                        (episodes, total_steps, p, hunger)
            # food respawn draws from each engine's own PRNG; re-sync the
            # device food to the host's so the transition rules (the thing
            # under test) stay in lockstep
            if len(host.food) < jhg.N_FOOD:
                break        # board too full to respawn: beyond the fixed-
                             # slot device representation (docs/geese_rules)
            dev = dev._replace(food=jnp.asarray([list(host.food)],
                                                jnp.int32))
            total_steps += 1
            da = np.asarray(dev.alive)[0]
            dl = np.asarray(dev.length)[0]
            dc = np.asarray(dev.cells)[0]
            assert list(da) == host.alive, (episodes, total_steps)
            for p in range(4):
                assert dl[p] == len(host.geese[p]), (episodes, total_steps)
                assert list(dc[p, :dl[p]]) == host.geese[p], \
                    (episodes, total_steps)
            # food: counts must match; cells differ (independent PRNGs)
            # only after a respawn, so compare pre-respawn contents via
            # the occupancy invariant instead
            df = np.asarray(dev.food)[0]
            assert len(set(df)) == len(set(host.food)) == jhg.N_FOOD or \
                host.terminal()
            occupied = {c for g in host.geese for c in g}
            for f in host.food:
                assert f not in occupied
        # outcome agreement at terminal
        if host.terminal():
            host_out = [host.outcome()[p] for p in range(4)]
            dev_out = list(np.asarray(jhg.outcome(dev))[0])
            assert host_out == pytest.approx(dev_out), (episodes,)
    assert total_steps >= 2600


def test_jax_greedy_agreement_on_random_positions():
    """Same agreement property as the trajectory test below, but over
    SYNTHETIC random positions (self-avoiding random walks for bodies,
    random food and last actions) — covering states random play from the
    start rarely reaches (long bodies, crowded boards)."""
    from handyrl_tpu.envs.kaggle.hungry_geese import _move
    from test_jax_geese import greedy_candidates

    rng = np.random.RandomState(11)
    greedy_fn = jax.jit(jhg.greedy_action)
    checked = 0
    for trial in range(500):
        # lay out 4 disjoint self-avoiding walks on the torus
        taken: set = set()
        geese = []
        for p in range(4):
            for _attempt in range(20):
                L = int(rng.randint(1, 7))
                cell = int(rng.randint(77))
                body = [cell]
                while len(body) < L:
                    nxt = _move(body[-1], int(rng.randint(4)))
                    if nxt in body or nxt in taken:
                        break
                    body.append(nxt)
                if body[0] not in taken and not (set(body) & taken):
                    break
            if set(body) & taken:
                body = []
            taken |= set(body)
            geese.append(body)
        if not any(geese):
            continue
        free = [c for c in range(77) if c not in taken]
        food = list(rng.choice(free, size=min(2, len(free)), replace=False))
        last = {p: int(rng.randint(4)) for p in range(4)
                if geese[p] and rng.rand() < 0.7}

        host = _host_with(geese, food, last_actions=last)
        dev = _manual_state(geese, food, last_actions=last)
        dev_acts = np.asarray(greedy_fn(dev, jax.random.PRNGKey(trial)))[0]
        for p in range(4):
            if not geese[p]:
                continue
            if not greedy_candidates(geese, food, last, p):
                continue            # both sides fall back randomly
            host_a = host.rule_based_action(p)
            checked += 1
            assert host_a == int(dev_acts[p]), (trial, p, geese, food, last)
    assert checked >= 800


def test_jax_greedy_agrees_with_host_rulebase():
    """The vectorized device GreedyAgent must choose the SAME action as the
    host behavioral port on every state where the host pick is not the
    random fallback (fallbacks draw from different PRNGs)."""
    from test_jax_geese import greedy_candidates

    rng = np.random.RandomState(7)
    step_fn = jax.jit(jhg.step)
    greedy_fn = jax.jit(jhg.greedy_action)
    checked = agreed = 0
    for ep in range(12):
        host = HostGeese({'id': int(rng.randint(1 << 30))})
        dev = _manual_state([list(g) for g in host.geese], list(host.food))
        while not host.terminal() and checked < 400:
            dev_acts = np.asarray(greedy_fn(dev, jax.random.PRNGKey(
                rng.randint(1 << 30))))[0]
            for p in host.turns():
                # detect the host fallback (no legal candidate) by
                # re-deriving the candidate set per the documented rules
                if not greedy_candidates(host.geese, host.food,
                                         host.last_actions, p):
                    continue            # both sides fall back randomly
                host_a = host.rule_based_action(p)
                checked += 1
                agreed += int(host_a == int(dev_acts[p]))
                assert host_a == int(dev_acts[p]), (ep, p, host.geese,
                                                    host.food)
            acts = {p: int(rng.randint(4)) for p in host.turns()}
            host.step(dict(acts))
            dev = step_fn(dev, jnp.asarray([[acts.get(p, 0)
                                             for p in range(4)]]))
            if len(host.food) < jhg.N_FOOD:
                break
            dev = dev._replace(food=jnp.asarray([list(host.food)],
                                                jnp.int32))
    assert checked >= 200 and agreed == checked
