"""Geister through the full pipeline: batched generation with recurrent
hidden state + dict observations, batch building, and the compiled update
step with burn-in (downsized DRC so CPU compiles stay fast)."""

import numpy as np
import jax
import pytest

from handyrl_tpu.environment import make_env
from handyrl_tpu.model import ModelWrapper
from handyrl_tpu.models.geister import GeisterNet
from handyrl_tpu.generation import BatchedGenerator, Generator
from handyrl_tpu.ops.batch import make_batch, select_episode
from handyrl_tpu.ops.losses import LossConfig
from handyrl_tpu.ops.train_step import build_update_step, init_train_state

ENV_ARGS = {'env': 'Geister'}


def _tiny_net():
    return GeisterNet(filters=8, drc_layers=2, drc_repeats=1)


def _gen_args(burn_in=0):
    return {
        'turn_based_training': True, 'observation': False,
        'gamma': 0.9, 'forward_steps': 8, 'burn_in_steps': burn_in,
        'compress_steps': 4, 'maximum_episodes': 100,
        'lambda': 0.7, 'policy_target': 'TD', 'value_target': 'TD',
        'entropy_regularization': 0.1, 'entropy_regularization_decay': 0.1,
    }


@pytest.fixture(scope='module')
def geister_episodes():
    env = make_env(ENV_ARGS)
    env.reset()
    wrapper = ModelWrapper(_tiny_net())
    wrapper.ensure_params(env.observation(0))
    gen = BatchedGenerator(lambda i: make_env(ENV_ARGS), wrapper, _gen_args(),
                           n_envs=4)
    episodes = []
    for _ in range(400):
        episodes += gen.step()
        if len(episodes) >= 3:
            break
    assert len(episodes) >= 3, 'batched generator produced no episodes'
    return wrapper, episodes


def test_geister_episode_structure(geister_episodes):
    _, episodes = geister_episodes
    ep = episodes[0]
    assert ep['steps'] >= 2
    assert set(ep['outcome'].keys()) == {0, 1}
    from handyrl_tpu.ops.batch import decompress_moments
    moments = decompress_moments(ep['moment'])
    m0 = moments[0]
    # setup ply: only the acting player observed/acted
    acting = m0['turn'][0]
    assert m0['action'][acting] is not None
    assert m0['observation'][acting]['board'].shape == (7, 6, 6)
    assert m0['action_mask'][acting].shape == (4 * 36 + 70,)


def test_geister_update_step_with_burn_in(geister_episodes):
    wrapper, episodes = geister_episodes
    args = _gen_args(burn_in=2)
    windows = [select_episode(episodes, args) for _ in range(2)]
    batch = make_batch(windows, args)
    assert batch['observation']['board'].shape[0] == 2
    assert batch['value'].shape[2] == 2         # both players' values kept

    module = _tiny_net()
    state = init_train_state(wrapper.params)
    cfg = LossConfig.from_args(args)
    step = build_update_step(module, cfg, donate=False)
    import jax.numpy as jnp
    state2, metrics = step(state, batch, jnp.asarray(1e-4, jnp.float32))
    for k in ('p', 'v', 'r', 'ent', 'total'):
        assert np.isfinite(float(metrics[k])), k
    diff = jax.tree_util.tree_reduce(
        lambda a, l: a + float(jnp.abs(l).sum()),
        jax.tree_util.tree_map(lambda a, b: a - b, state.params, state2.params),
        0.0)
    assert diff > 0


def test_sequential_generator_matches_contract():
    env = make_env(ENV_ARGS)
    wrapper = ModelWrapper(_tiny_net())
    env.reset()
    wrapper.ensure_params(env.observation(0))
    gen = Generator(env, _gen_args())
    models = {0: wrapper, 1: wrapper}
    ep = gen.generate(models, {'player': [0, 1],
                               'model_id': {0: 1, 1: 1}})
    assert ep is not None
    assert ep['steps'] >= 2
