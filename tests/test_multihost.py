"""Multi-host wiring test: two real processes join one jax.distributed job
on the CPU backend, see the global device set, and run a cross-process
collective. This validates the path train_main activates via
``_init_multihost`` (train.py) / ``multihost.initialize`` before any JAX
use — the learner-side counterpart of the reference's multi-node story
(which only ever distributes CPU actors, reference worker.py:185-254)."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, %(repo)r)
from handyrl_tpu.parallel import multihost

ok = multihost.initialize()          # resolved from JAX_COORDINATOR_ADDRESS
assert ok, 'env-driven initialize() should activate'
assert multihost.is_coordinator() == (jax.process_index() == 0)

import jax.numpy as jnp
from jax.experimental import multihost_utils
# one real cross-process collective: everyone receives process 0's value
val = multihost_utils.broadcast_one_to_all(
    jnp.asarray(100.0 + jax.process_index()))
print('OK', jax.process_index(), jax.process_count(), jax.device_count(),
      float(val), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(('', 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.timeout(180)
def test_two_process_jax_distributed_cpu(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / 'child.py'
    script.write_text(_CHILD % {'repo': repo})
    port = _free_port()

    children = []
    for pid in range(2):
        env = dict(os.environ,
                   JAX_PLATFORMS='cpu',
                   JAX_COORDINATOR_ADDRESS='localhost:%d' % port,
                   JAX_NUM_PROCESSES='2',
                   JAX_PROCESS_ID=str(pid))
        env.pop('XLA_FLAGS', None)   # 1 device per process, no virtual mesh
        children.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    outputs = []
    for proc in children:
        out, _ = proc.communicate(timeout=150)
        outputs.append(out)
        assert proc.returncode == 0, out

    for pid, out in enumerate(outputs):
        line = next(l for l in out.splitlines() if l.startswith('OK'))
        _, idx, count, devices, val = line.split()
        assert int(idx) == pid
        assert int(count) == 2
        assert int(devices) == 2          # global view: one CPU device each
        assert float(val) == 100.0        # coordinator's value won


_TRAIN_CHILD = r"""
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, %(repo)r)
from handyrl_tpu.parallel import multihost

ok = multihost.initialize()
assert ok, 'env-driven initialize() should activate'

import hashlib
import numpy as np
import jax.numpy as jnp
from __graft_entry__ import _synthetic_batch
from handyrl_tpu.models import build
from handyrl_tpu.ops.losses import LossConfig
from handyrl_tpu.ops.train_step import build_update_step, init_train_state
from handyrl_tpu.parallel import partition

# the global 2-device mesh (1 CPU device per process)
mesh = multihost.global_mesh()
assert int(np.prod(list(mesh.shape.values()))) == 2, mesh

# identical construction on both processes: params replicate, and each
# process contributes its OWN half of the global batch
module = build('SimpleConv2dModel')
rng = np.random.RandomState(0)
gbatch = _synthetic_batch(8, 4, 1, (3, 3, 3), 9, rng)
params = module.init(jax.random.PRNGKey(0),
                     gbatch['observation'][:, 0, 0], None)
state = init_train_state(params)
cfg = LossConfig(turn_based_training=False, observation=True,
                 policy_target='TD', value_target='TD', gamma=0.9)
shardings = partition.tree_shardings(mesh, state, partition.DEFAULT_RULES)
step = build_update_step(module, cfg, mesh=mesh, donate=False,
                         state_shardings=shardings)

pid = jax.process_index()
local = jax.tree_util.tree_map(lambda x: x[4 * pid:4 * (pid + 1)], gbatch)
batch = partition.host_to_global_batch(mesh, local)
state2, metrics = step(state, batch,
                       jnp.asarray(1e-4, jnp.float32))

# every leaf is replicated: hash THIS process's local replica; the parent
# asserts both processes hold bit-identical updated params
h = hashlib.sha1()
for path, leaf in sorted(
        jax.tree_util.tree_flatten_with_path(state2.params)[0],
        key=lambda kv: str(kv[0])):
    h.update(np.asarray(leaf.addressable_shards[0].data).tobytes())
print('OK', jax.process_index(), int(state2.steps.addressable_shards[0].data),
      h.hexdigest(),
      float(np.asarray(metrics['total'].addressable_shards[0].data)),
      flush=True)
"""


@pytest.mark.timeout(240)
def test_two_process_sharded_train_step(tmp_path):
    """The learner-side multi-host story end to end on a 2-process CPU
    mesh: jax.distributed via parallel/multihost.py (gloo collectives), the
    partition-rule-built NamedSharding train step over the global mesh,
    each process feeding its local batch shard — and both processes ending
    with bit-identical replicated params."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / 'train_child.py'
    script.write_text(_TRAIN_CHILD % {'repo': repo})
    port = _free_port()

    children = []
    for pid in range(2):
        env = dict(os.environ,
                   JAX_PLATFORMS='cpu',
                   JAX_COORDINATOR_ADDRESS='localhost:%d' % port,
                   JAX_NUM_PROCESSES='2',
                   JAX_PROCESS_ID=str(pid))
        env.pop('XLA_FLAGS', None)   # 1 device per process
        children.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    outputs = []
    for proc in children:
        out, _ = proc.communicate(timeout=210)
        outputs.append(out)
        assert proc.returncode == 0, out

    rows = []
    for pid, out in enumerate(outputs):
        line = next(l for l in out.splitlines() if l.startswith('OK'))
        _, idx, steps, digest, loss = line.split()
        assert int(idx) == pid
        assert int(steps) == 1           # one SGD step applied everywhere
        rows.append((digest, float(loss)))
    # identical replicated params AND identical (psum'd) loss on both hosts
    assert rows[0][0] == rows[1][0]
    assert rows[0][1] == pytest.approx(rows[1][1], rel=1e-6)


def test_initialize_noop_without_configuration(monkeypatch):
    for var in ('JAX_COORDINATOR_ADDRESS', 'COORDINATOR_ADDRESS',
                'MEGASCALE_COORDINATOR_ADDRESS'):
        monkeypatch.delenv(var, raising=False)
    from handyrl_tpu.parallel import multihost
    assert multihost.initialize() is False
