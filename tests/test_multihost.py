"""Multi-host wiring test: two real processes join one jax.distributed job
on the CPU backend, see the global device set, and run a cross-process
collective. This validates the path train_main activates via
``_init_multihost`` (train.py) / ``multihost.initialize`` before any JAX
use — the learner-side counterpart of the reference's multi-node story
(which only ever distributes CPU actors, reference worker.py:185-254)."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, %(repo)r)
from handyrl_tpu.parallel import multihost

ok = multihost.initialize()          # resolved from JAX_COORDINATOR_ADDRESS
assert ok, 'env-driven initialize() should activate'
assert multihost.is_coordinator() == (jax.process_index() == 0)

import jax.numpy as jnp
from jax.experimental import multihost_utils
# one real cross-process collective: everyone receives process 0's value
val = multihost_utils.broadcast_one_to_all(
    jnp.asarray(100.0 + jax.process_index()))
print('OK', jax.process_index(), jax.process_count(), jax.device_count(),
      float(val), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(('', 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.timeout(180)
def test_two_process_jax_distributed_cpu(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / 'child.py'
    script.write_text(_CHILD % {'repo': repo})
    port = _free_port()

    children = []
    for pid in range(2):
        env = dict(os.environ,
                   JAX_PLATFORMS='cpu',
                   JAX_COORDINATOR_ADDRESS='localhost:%d' % port,
                   JAX_NUM_PROCESSES='2',
                   JAX_PROCESS_ID=str(pid))
        env.pop('XLA_FLAGS', None)   # 1 device per process, no virtual mesh
        children.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    outputs = []
    for proc in children:
        out, _ = proc.communicate(timeout=150)
        outputs.append(out)
        assert proc.returncode == 0, out

    for pid, out in enumerate(outputs):
        line = next(l for l in out.splitlines() if l.startswith('OK'))
        _, idx, count, devices, val = line.split()
        assert int(idx) == pid
        assert int(count) == 2
        assert int(devices) == 2          # global view: one CPU device each
        assert float(val) == 100.0        # coordinator's value won


def test_initialize_noop_without_configuration(monkeypatch):
    for var in ('JAX_COORDINATOR_ADDRESS', 'COORDINATOR_ADDRESS',
                'MEGASCALE_COORDINATOR_ADDRESS'):
        monkeypatch.delenv(var, raising=False)
    from handyrl_tpu.parallel import multihost
    assert multihost.initialize() is False
