"""Vectorized model-vs-model evaluation: BatchedEvaluator must accept a
checkpoint path as the opponent spec, load it once, batch its seats like the
trained seat's, and produce valid result records. (The reference has no
vectorized model-vs-model path at all — its eval.opponent models only run
through the sequential offline harness.)"""

import random

import numpy as np

from handyrl_tpu.environment import make_env
from handyrl_tpu.generation import BatchedEvaluator
from handyrl_tpu.model import ModelWrapper


def _make_wrapper(env):
    env.reset()
    wrapper = ModelWrapper(env.net())
    wrapper.ensure_params(env.observation(env.players()[0]))
    return wrapper


def _run(evaluator, want_results=8, max_steps=600):
    results = []
    for _ in range(max_steps):
        results.extend(evaluator.step())
        if len(results) >= want_results:
            break
    return results


def test_model_opponent_from_checkpoint(tmp_path):
    random.seed(0)
    env = make_env({'env': 'TicTacToe'})
    wrapper = _make_wrapper(env)
    ckpt = tmp_path / 'opp.ckpt'
    ckpt.write_bytes(wrapper.params_bytes())

    evaluator = BatchedEvaluator(
        lambda i: make_env({'env': 'TicTacToe', 'id': i}),
        wrapper,
        {'eval': {'opponent': [str(ckpt)]}},
        n_envs=8)

    results = _run(evaluator)
    assert len(results) >= 8
    # the checkpoint opponent was loaded exactly once into the pool
    assert str(ckpt) in evaluator._model_pool
    assert len(evaluator._model_pool) == 2   # main + one opponent
    for rec in results:
        assert rec['opponent'] == str(ckpt)
        outcome = rec['result']
        assert abs(sum(outcome.values())) < 1e-9   # zero-sum
        seat = rec['args']['player'][0]
        assert rec['args']['model_id'][seat] == 0


def test_mixed_opponent_pool(tmp_path):
    """Host agents and model opponents can share the pool; every match
    reports which opponent it drew."""
    random.seed(1)
    env = make_env({'env': 'TicTacToe'})
    wrapper = _make_wrapper(env)
    ckpt = tmp_path / 'opp.ckpt'
    ckpt.write_bytes(wrapper.params_bytes())

    evaluator = BatchedEvaluator(
        lambda i: make_env({'env': 'TicTacToe', 'id': i}),
        wrapper,
        {'eval': {'opponent': ['random', str(ckpt)]}},
        n_envs=8)

    results = _run(evaluator, want_results=20, max_steps=1200)
    drawn = {rec['opponent'] for rec in results}
    assert drawn == {'random', str(ckpt)}


def test_identical_models_draw_or_split_symmetrically(tmp_path):
    """Self-play through the model-opponent path: seats rotate, outcomes
    stay zero-sum, and greedy-vs-greedy with identical params is
    deterministic per seat assignment."""
    random.seed(2)
    env = make_env({'env': 'TicTacToe'})
    wrapper = _make_wrapper(env)
    ckpt = tmp_path / 'self.ckpt'
    ckpt.write_bytes(wrapper.params_bytes())

    evaluator = BatchedEvaluator(
        lambda i: make_env({'env': 'TicTacToe', 'id': i}),
        wrapper,
        {'eval': {'opponent': [str(ckpt)]}},
        n_envs=4)
    results = _run(evaluator, want_results=8)
    by_seat = {}
    for rec in results:
        seat = rec['args']['player'][0]
        by_seat.setdefault(seat, set()).add(rec['result'][seat])
    # identical greedy policies: every match with the same seat assignment
    # plays the same game, so outcomes per seat are a single value
    for seat, outcomes in by_seat.items():
        assert len(outcomes) == 1


def test_worker_mode_evaluator_accepts_checkpoint_opponent(tmp_path):
    """The sequential (worker-mode) Evaluator resolves checkpoint specs the
    same way the batched front-end does, caching the loaded model."""
    from handyrl_tpu.evaluation import Evaluator
    random.seed(3)
    env = make_env({'env': 'TicTacToe'})
    wrapper = _make_wrapper(env)
    ckpt = tmp_path / 'opp.ckpt'
    ckpt.write_bytes(wrapper.params_bytes())

    ev = Evaluator(env, {'eval': {'opponent': [str(ckpt)]}})
    for seat in (0, 1):
        models = {seat: wrapper, 1 - seat: None}
        rec = ev.execute(models, {'role': 'e', 'player': [seat]})
        assert rec is not None
        assert rec['opponent'] == str(ckpt)
        assert abs(sum(rec['result'].values())) < 1e-9
    assert len(ev._opponent_cache) == 1   # loaded once, reused
