"""Episode-lifecycle distributed tracing: sampling determinism, the
trace-context propagation chain (task_assign -> generate -> upload ->
ingest -> train_step) through the real ledger/gather/batcher components,
policy-lag accounting at window selection, and (slow) the full TCP fleet
whose one trace file links spans from >= 3 processes by shared trace_ids
while policy_lag / rho_clip_fraction land in metrics_jsonl and /metrics.
"""

import glob
import json
import os
import subprocess
import sys
import time
import urllib.request
from collections import deque

import numpy as np
import pytest

from handyrl_tpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def trace_dir(tmp_path):
    """Route tracing into a tmp dir for the duration of one test, then
    restore the off state (other tests must see tracing disabled)."""
    d = str(tmp_path / 'traces')
    telemetry.configure_tracing(d, 1.0, force=True)
    try:
        yield d
    finally:
        telemetry.trace_flush()
        telemetry.configure_tracing('', 1.0, force=True)
        os.environ.pop('HANDYRL_TPU_TRACE', None)
        os.environ.pop('HANDYRL_TPU_TRACE_RATE', None)


def read_events(d):
    telemetry.trace_flush()
    events = []
    for path in glob.glob(os.path.join(d, 'trace-*.jsonl')):
        for line in open(path):
            if line.strip():
                events.append(json.loads(line))
    return events


# ---------------------------------------------------------------------------
# trace id + sampling


def test_episode_trace_id_derivation():
    assert telemetry.episode_trace_id({'role': 'g', 'sample_key': 7}) == 'g7'
    assert telemetry.episode_trace_id({'role': 'e', 'sample_key': 0}) == 'e0'
    # no server-stamped sample_key -> no trace context
    assert telemetry.episode_trace_id({'role': 'g'}) is None
    assert telemetry.episode_trace_id(None) is None
    assert telemetry.episode_trace_id('not-a-dict') is None


def test_sampling_is_deterministic_and_rate_shaped(trace_dir):
    # rate 1: everything kept; rate 0: nothing; fractional: deterministic
    assert telemetry.trace_sampled('g1')
    telemetry.configure_tracing(trace_dir, 0.0, force=True)
    assert not telemetry.trace_sampled('g1')
    telemetry.configure_tracing(trace_dir, 0.25, force=True)
    ids = ['g%d' % i for i in range(400)]
    kept = [i for i in ids if telemetry.trace_sampled(i)]
    # deterministic: the same decision on every call (every process)
    assert kept == [i for i in ids if telemetry.trace_sampled(i)]
    assert 40 < len(kept) < 160          # ~25% of 400
    # unsampled ids produce no events
    telemetry.trace_event('generate', trace_id=(set(ids) - set(kept)).pop())
    telemetry.trace_event('generate', trace_id=kept[0])
    events = [e for e in read_events(trace_dir) if e['name'] == 'generate']
    assert len(events) == 1
    assert events[0]['args']['trace_id'] == kept[0]


def test_tracing_off_is_inert(tmp_path):
    telemetry.configure_tracing('', 1.0, force=True)
    assert not telemetry.trace_enabled()
    assert not telemetry.trace_sampled('g1')
    telemetry.trace_event('generate', trace_id='g1')   # must be a no-op
    with telemetry.trace_span('generate', trace_id='g1'):
        pass
    telemetry.trace_flush()
    telemetry.finalize_trace()


def test_trace_span_records_stage_histogram_and_event(trace_dir):
    before = telemetry.REGISTRY.histogram('stage_seconds',
                                          stage='unit_span').count
    with telemetry.trace_span('unit_span', trace_id='g3'):
        time.sleep(0.01)
    hist = telemetry.REGISTRY.histogram('stage_seconds', stage='unit_span')
    assert hist.count == before + 1
    ev = [e for e in read_events(trace_dir) if e['name'] == 'unit_span']
    assert len(ev) == 1
    assert ev[0]['dur'] >= 10000          # microseconds
    assert ev[0]['args']['trace_id'] == 'g3'
    assert ev[0]['args']['run_id'] == telemetry.run_id()


# ---------------------------------------------------------------------------
# propagation: one synthetic episode through ledger -> gather -> batcher


def _synthetic_task_episode(sample_key=7, model_epoch=1):
    """One geese-geometry episode stamped like a served generation task."""
    sys.path.insert(0, REPO)
    from bench import _synthetic_geese_episodes
    rng = np.random.RandomState(3)
    ep = _synthetic_geese_episodes(1, rng, min_steps=24, max_steps=24)[0]
    players = ep['args']['player']
    ep['args'] = {'role': 'g', 'player': players,
                  'model_id': {p: model_epoch for p in players},
                  'sample_key': sample_key}
    return ep


def test_trace_context_propagates_gather_ledger_batcher(trace_dir):
    """The unit half of the propagation satellite: one synthetic episode
    rides the REAL components — TaskLedger.assign/admit (learner),
    UploadTrace (gather), Batcher/TracedBatch (trainer) — and every span
    shares the derived trace_id with causally ordered stages."""
    from handyrl_tpu.fault import TaskLedger
    from handyrl_tpu.train import Batcher, TracedBatch
    from handyrl_tpu.worker import UploadTrace

    ep = _synthetic_task_episode(sample_key=7)
    tid = telemetry.episode_trace_id(ep['args'])
    assert tid == 'g7'

    # learner: assignment books the task and births the trace context
    ledger = TaskLedger()
    endpoint = object()
    ledger.assign(endpoint, ep['args'])
    assert 'task_id' in ep['args']

    # worker: the generate span (the real Generator.execute wraps exactly
    # this call around env stepping)
    with telemetry.trace_span('generate', trace_id=tid):
        time.sleep(0.002)

    # gather: stash -> server-ack upload span
    upload = UploadTrace(gather_id=0)
    upload.stash('episode', ep)
    upload.shipped('episode')

    # learner: ledger delivery (the ingest event) + consumption stamp
    admitted = ledger.admit([ep])
    assert admitted == [ep]
    ep['recv_time'] = time.time()

    # trainer: the batcher selects/builds and wraps the trace ids; the
    # train_step event carries them (what Trainer.train emits at dispatch)
    args = {'turn_based_training': False, 'observation': True,
            'forward_steps': 8, 'burn_in_steps': 0, 'compress_steps': 4,
            'maximum_episodes': 1000, 'batch_size': 2, 'num_batchers': 1}
    batcher = Batcher(args, deque([ep]))
    batcher.run()
    try:
        wrapped = batcher.batch(timeout=60)
    finally:
        batcher.stop()
    assert isinstance(wrapped, TracedBatch)
    assert wrapped.trace_ids == [tid]
    telemetry.trace_event('train_step', dur=0.001, always=True,
                          trace_ids=wrapped.trace_ids, steps=1)

    # duplicate admission must NOT re-emit the ingest hop
    assert ledger.admit([dict(ep)]) == []

    events = read_events(trace_dir)
    by_stage = {}
    for e in events:
        a = e.get('args') or {}
        if a.get('trace_id') == tid or tid in (a.get('trace_ids') or ()):
            by_stage.setdefault(e['name'], []).append(e)
    for stage in ('task_assign', 'generate', 'upload', 'ingest',
                  'train_step'):
        assert stage in by_stage, 'missing %s span for %s' % (stage, tid)
        assert len(by_stage[stage]) == 1
    # causal nesting: each hop starts no earlier than the previous one
    order = [by_stage[s][0]['ts'] for s in
             ('task_assign', 'generate', 'upload', 'ingest', 'train_step')]
    assert order == sorted(order), order
    # the upload span COVERS its stash->ack residence (ingest falls after)
    up = by_stage['upload'][0]
    assert by_stage['ingest'][0]['ts'] >= up['ts']

    # trace_report sees one complete chain over these events
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'trace_report.py'),
         trace_dir, '--json'], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout)
    assert report['complete_chains'] == 1
    assert report['order_violations'] == 0
    assert report['generation_to_gradient_seconds']['n'] == 1


def test_shm_descriptor_carries_trace_ids(trace_dir):
    from handyrl_tpu.ops.shm_batch import SharedBatch
    sb = SharedBatch({'x': 1}, lambda: None, trace_ids=['g7'])
    assert sb.trace_ids == ['g7']
    assert SharedBatch({'x': 1}, lambda: None).trace_ids is None


# ---------------------------------------------------------------------------
# policy-lag accounting at window selection


def test_batcher_observes_policy_lag_and_sample_age():
    from handyrl_tpu.train import Batcher

    ep = _synthetic_task_episode(sample_key=9, model_epoch=2)
    ep['recv_time'] = time.time() - 5.0
    args = {'turn_based_training': False, 'observation': True,
            'forward_steps': 8, 'burn_in_steps': 0, 'compress_steps': 4,
            'maximum_episodes': 1000, 'batch_size': 2, 'num_batchers': 1}
    batcher = Batcher(args, deque([ep]))
    batcher.epoch_fn = lambda: 6
    lag0, lag_sum0 = batcher._m_lag.count, batcher._m_lag.sum
    age0, age_sum0 = batcher._m_age.count, batcher._m_age.sum
    batcher.run()
    try:
        batcher.batch(timeout=60)
    finally:
        batcher.stop()
    # batch_size=2 windows from the one episode: 2 selections, 4 players
    # each -> 8 lag observations of (6 - 2) = 4 epochs, 2 age observations
    assert batcher._m_lag.count >= lag0 + 8
    lag_mean = ((batcher._m_lag.sum - lag_sum0)
                / (batcher._m_lag.count - lag0))
    assert abs(lag_mean - 4.0) < 1e-6
    assert batcher._m_age.count >= age0 + 2
    age_mean = ((batcher._m_age.sum - age_sum0)
                / (batcher._m_age.count - age0))
    assert 4.0 < age_mean < 30.0


# ---------------------------------------------------------------------------
# slow: the real TCP fleet writes one linked multi-process trace


LEARNER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from handyrl_tpu.config import apply_defaults
    from handyrl_tpu.train import Learner
    raw = {'env_args': {'env': 'TicTacToe'},
           'train_args': {'batch_size': 8, 'update_episodes': 12,
                          'minimum_episodes': 12, 'epochs': 2,
                          'forward_steps': 8, 'num_batchers': 1,
                          'model_dir': %(model_dir)r,
                          'metrics_jsonl': %(metrics)r,
                          'telemetry_port': %(port)d,
                          'fault_tolerance': {'heartbeat_interval': 1.0,
                                              'liveness_timeout': 15.0}}}
    learner = Learner(args=apply_defaults(raw), remote=True)
    learner.run()
    print('LEARNER DONE', learner.model_epoch, flush=True)

if __name__ == '__main__':
    main()
'''

WORKER_SCRIPT = r'''
import os
os.environ['JAX_PLATFORMS'] = 'cpu'

def main():
    from handyrl_tpu.worker import worker_main
    args = {'worker_args': {'server_address': 'localhost', 'num_parallel': 2}}
    worker_main(args, [])

if __name__ == '__main__':
    main()
'''


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_fleet_trace_links_three_processes(tmp_path):
    """Learner + worker host over real TCP with HANDYRL_TPU_TRACE set: one
    trace file must hold spans from >= 3 distinct processes (learner,
    gather, worker) linked by shared trace_ids covering
    task_assign -> generate -> upload -> ingest (-> train_step), the
    collated Chrome JSON must parse, trace_report must find a non-empty
    generation->gradient critical path, and policy_lag /
    rho_clip_fraction must appear per epoch in metrics_jsonl AND in the
    live Prometheus exposition."""
    entry_port, data_port, prom_port = 23210, 23211, 23212
    trace_d = str(tmp_path / 'traces')
    metrics = str(tmp_path / 'metrics.jsonl')
    learner_py = tmp_path / 'learner.py'
    worker_py = tmp_path / 'worker.py'
    learner_py.write_text(LEARNER_SCRIPT % {
        'model_dir': str(tmp_path / 'models'), 'metrics': metrics,
        'port': prom_port})
    worker_py.write_text(WORKER_SCRIPT)

    env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
           'HANDYRL_TPU_TRACE': trace_d, 'HANDYRL_TPU_TRACE_RATE': '1.0',
           'HANDYRL_TPU_ENTRY_PORT': str(entry_port),
           'HANDYRL_TPU_DATA_PORT': str(data_port),
           'PYTHONPATH': REPO + os.pathsep + os.environ.get('PYTHONPATH', '')}
    learner_log = open(tmp_path / 'learner.log', 'w')
    worker_log = open(tmp_path / 'worker.log', 'w')
    learner = subprocess.Popen([sys.executable, str(learner_py)], env=env,
                               stdout=learner_log, stderr=subprocess.STDOUT)
    worker = None
    exposition = ''
    try:
        time.sleep(3)
        worker = subprocess.Popen([sys.executable, str(worker_py)], env=env,
                                  stdout=worker_log,
                                  stderr=subprocess.STDOUT)
        deadline = time.time() + 240
        url = 'http://127.0.0.1:%d/metrics' % prom_port
        while time.time() < deadline and learner.poll() is None:
            try:
                body = urllib.request.urlopen(url, timeout=5).read().decode()
                if 'rho_clip_fraction' in body and 'policy_lag' in body:
                    exposition = body
                    break
                exposition = exposition or body
            except OSError:
                pass
            time.sleep(2)
        assert learner.wait(timeout=300) == 0
        worker.wait(timeout=120)
    finally:
        for proc in (worker, learner):
            if proc is not None and proc.poll() is None:
                proc.kill()
        learner_log.close()
        worker_log.close()

    # learning-dynamics + policy-lag metrics per epoch in metrics_jsonl
    lines = [json.loads(l) for l in open(metrics) if l.strip()]
    assert lines
    last = lines[-1]
    for key in ('policy_lag', 'rho_clip_fraction', 'entropy', 'grad_norm'):
        assert key in last, 'metrics_jsonl missing %s: %s' % (key, last)
    assert 0.0 <= last['rho_clip_fraction'] <= 1.0
    # ... and live on the exporter while the run was up
    assert 'rho_clip_fraction' in exposition
    assert 'policy_lag' in exposition

    # the collated Chrome trace parses and links >= 3 processes by id
    finalized = glob.glob(os.path.join(trace_d, 'trace-*.json'))
    assert finalized, 'learner did not collate the Chrome trace'
    events = json.load(open(finalized[0]))['traceEvents']
    assert events
    sys.path.insert(0, os.path.join(REPO, 'scripts'))
    import trace_report
    chains = trace_report.build_chains(events)
    full = 0
    linked_pids = set()
    for tid, stages in chains.items():
        assert not trace_report.chain_errors(stages), (tid, stages)
        linked_pids.update(pid for _ts, _dur, pid in stages.values())
        if {'task_assign', 'generate', 'upload', 'ingest'} <= set(stages):
            full += 1
    assert len(linked_pids) >= 3, \
        'want spans from learner+gather+worker, got %d pids' % len(linked_pids)
    assert full >= 1

    # trace_report: non-empty generation->gradient critical path
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'trace_report.py'),
         trace_d, '--json'], capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout)
    assert report['complete_chains'] >= 1
    assert report['processes'] >= 3
    assert report['generation_to_gradient_seconds']['n'] >= 1
