"""graftlint: every checker must catch a seeded violation AND pass its
clean twin; the sanitizer must detect a deliberate ABBA inversion without
hanging; and the CI gate itself must be green on the real tree.

Fixture trees are laid out under tmp_path with the same repo-relative
shapes the scope tables key on (``handyrl_tpu/generation.py`` etc.), so
the fixtures exercise exactly the production scoping logic.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from handyrl_tpu.analysis import (collect_sources, repo_root, run_checks,
                                  run_lint)
from handyrl_tpu.analysis import sanitizer as sz
from handyrl_tpu.analysis.core import (SourceFile, apply_suppressions,
                                       load_baseline)
from handyrl_tpu.analysis.checkers import (check_gl001, check_gl002,
                                           check_gl003, check_gl004)
from handyrl_tpu.analysis.vocabulary import check_gl005


def _src(path, text):
    return SourceFile(path, text)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# GL001 determinism


GL001_DIRTY = '''
import random
import time
import numpy as np

def pick(xs):
    t = time.time()
    if random.random() < 0.5:
        return random.choice(xs), t
    return xs[np.random.randint(len(xs))], t
'''

GL001_CLEAN = '''
import random
import numpy as np
import time

def pick(xs, seed_seq):
    rng = np.random.default_rng(seed_seq)
    local = random.Random(7)
    t = time.perf_counter()
    return xs[int(rng.integers(len(xs)))], local.random(), t
'''


def test_gl001_flags_unseeded_draws_and_wall_clock():
    findings = check_gl001(_src('handyrl_tpu/generation.py', GL001_DIRTY))
    msgs = ' | '.join(f.message for f in findings)
    assert len(findings) == 4
    assert 'random.random' in msgs and 'random.choice' in msgs
    assert 'np.random.randint' in msgs and 'time.time' in msgs


def test_gl001_clean_twin_passes():
    assert check_gl001(_src('handyrl_tpu/generation.py', GL001_CLEAN)) == []


def test_gl001_out_of_scope_file_ignored():
    assert run_checks(
        {'handyrl_tpu/utils/timing.py':
         _src('handyrl_tpu/utils/timing.py', GL001_DIRTY)},
        rules=('GL001',)) == []


# ---------------------------------------------------------------------------
# GL002 host syncs in compiled code


GL002_DIRTY = '''
import jax
import jax.numpy as jnp
import numpy as np

def helper(x):
    return float(x) + x.item()

@jax.jit
def step(x):
    y = helper(x)
    if jnp.any(y > 0):
        y = np.asarray(y)
    return y
'''

GL002_BUILDER_DIRTY = '''
import jax
import jax.numpy as jnp

def build():
    def update(x):
        return int(x) + 1
    return update

fn = jax.jit(build())
'''

GL002_CLEAN = '''
import jax
import jax.numpy as jnp
import numpy as np

def host_drain(dev):
    # not traced: plain host code may materialize and coerce freely
    vals = np.asarray(dev)
    return float(vals[0]), int(vals[1])

@jax.jit
def step(x):
    y = jnp.where(jnp.any(x > 0), x, -x)
    return y.astype(jnp.float32)
'''


def test_gl002_flags_syncs_transitively_and_in_builders():
    path = 'handyrl_tpu/ops/train_step.py'
    findings = check_gl002({path: _src(path, GL002_DIRTY)})
    msgs = ' | '.join(f.message for f in findings)
    assert '.item()' in msgs                      # helper called from jit
    assert 'float() coercion' in msgs
    assert 'np.asarray' in msgs
    assert 'branching on a traced value' in msgs

    findings = check_gl002({path: _src(path, GL002_BUILDER_DIRTY)})
    assert any('int() coercion' in f.message for f in findings)


def test_gl002_clean_twin_passes_and_host_code_is_free():
    path = 'handyrl_tpu/ops/train_step.py'
    assert check_gl002({path: _src(path, GL002_CLEAN)}) == []


def test_gl002_real_tree_is_clean():
    sources = collect_sources(repo_root())
    assert check_gl002(sources) == []


def test_gl002_traced_set_covers_partition_built_train_step():
    """The NamedSharding/pjit entry points stay inside the no-host-sync
    contract: the partition modules are GL002-scoped, and the traced-
    function closure picks up the rule-built train step (the `update` the
    builders hand to jax.jit with in/out shardings) plus the fused replay
    program."""
    import ast

    from handyrl_tpu.analysis.checkers import (SCOPE_GL002, _parse,
                                               _traced_functions, in_scope)

    assert in_scope('handyrl_tpu/parallel/partition.py', SCOPE_GL002)
    assert in_scope('handyrl_tpu/parallel/mesh.py', SCOPE_GL002)

    sources = collect_sources(repo_root())
    scoped = {p: s for p, s in sources.items() if in_scope(p, SCOPE_GL002)}
    trees = {p: t for p, s in scoped.items()
             if (t := _parse(s)) is not None}
    traced = _traced_functions(trees)
    step_names = {n.name for n in traced['handyrl_tpu/ops/train_step.py']
                  if isinstance(n, ast.FunctionDef)}
    assert 'update' in step_names      # build_update_step's jitted core
    assert 'fused' in step_names       # build_replay_update's K-step scan
    # and through the cross-module closure, the loss math it calls
    assert any(isinstance(n, ast.FunctionDef)
               for n in traced['handyrl_tpu/ops/losses.py'])


# ---------------------------------------------------------------------------
# GL003 raw write-mode open


GL003_DIRTY = '''
def save(path, data):
    with open(path, 'wb') as f:
        f.write(data)

def log(path, line):
    open(path, mode='a').write(line)
'''

GL003_CLEAN = '''
from .utils.fs import atomic_write_bytes, append_jsonl

def save(path, data):
    atomic_write_bytes(path, data)

def load(path):
    with open(path, 'rb') as f:
        return f.read()

def peek(path):
    return open(path).read()
'''


def test_gl003_flags_write_modes_only():
    findings = check_gl003(_src('handyrl_tpu/train.py', GL003_DIRTY))
    assert len(findings) == 2
    assert check_gl003(_src('handyrl_tpu/train.py', GL003_CLEAN)) == []


def test_gl003_fs_module_is_the_sanctioned_site():
    assert check_gl003(_src('handyrl_tpu/utils/fs.py', GL003_DIRTY)) == []


# ---------------------------------------------------------------------------
# GL004 lock discipline


GL004_DIRTY = '''
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._peers = {}     # guarded-by: _lock

    def count(self):
        return len(self._peers)

    def run(self):
        threading.Thread(target=self.count, daemon=True).start()
'''

GL004_CLEAN = '''
import threading

class Hub:
    def __init__(self):
        self._lock = threading.Lock()
        self._peers = {}     # guarded-by: _lock

    def count(self):
        with self._lock:
            return len(self._peers)

    def _sweep_locked(self):
        self._peers.clear()

    def run(self):
        threading.Thread(target=self.count, name='hub-count',
                         daemon=True).start()
'''


def test_gl004_flags_unlocked_access_and_anonymous_thread():
    findings = check_gl004(_src('handyrl_tpu/connection.py', GL004_DIRTY))
    assert any('guarded-by _lock' in f.message for f in findings)
    assert any('without name=' in f.message for f in findings)


def test_gl004_clean_twin_with_locked_helper_passes():
    assert check_gl004(_src('handyrl_tpu/connection.py', GL004_CLEAN)) == []


# ---------------------------------------------------------------------------
# GL005 vocabulary drift (mini doc/config/source tree)


_OBS_DOC = '''# Observability
## Metric catalog
| name | type |
|---|---|
| `documented_total` | counter |
| `ghost_total` | counter |
## Span stage glossary
| stage | meaning |
|---|---|
| `select` | selection |
'''

_PARAM_DOC = '''# Parameters
## `train_args`
| key | default | meaning |
|---|---|---|
| `gamma` | 0.8 | discount |
| `phantom_knob` | 1 | no longer exists |
'''

_CONFIG = '''
TRAIN_DEFAULTS = {
    'gamma': 0.8,
    'undocumented_knob': 3,
}

def validate(args):
    ta = args['train_args']
    assert float(ta.get('gamma')) > 0
    assert ta.get('typo_knob') is None
'''

_EMITTER = '''
from . import telemetry

C = telemetry.counter('documented_total')
U = telemetry.counter('undocumented_total')

def f():
    with telemetry.trace_span('undocumented_stage'):
        pass
'''


def _gl005_tree(emitter=_EMITTER, config=_CONFIG, obs=_OBS_DOC,
                params=_PARAM_DOC):
    return {
        'docs/observability.md': _src('docs/observability.md', obs),
        'docs/parameters.md': _src('docs/parameters.md', params),
        'handyrl_tpu/config.py': _src('handyrl_tpu/config.py', config),
        'handyrl_tpu/train.py': _src('handyrl_tpu/train.py', emitter),
    }


def test_gl005_catches_every_drift_direction():
    msgs = [f.message for f in check_gl005(_gl005_tree())]
    blob = ' | '.join(msgs)
    assert "'undocumented_total'" in blob          # code -> missing doc row
    assert "'undocumented_stage'" in blob          # stage -> missing glossary
    assert "'ghost_total'" in blob                 # doc -> emitted nowhere
    assert "'undocumented_knob'" in blob           # default -> missing row
    assert "'phantom_knob'" in blob                # doc row -> no default
    assert "'typo_knob'" in blob                   # validate() -> unknown key


def test_gl005_clean_tree_passes():
    clean_emitter = '''
from . import telemetry
C = telemetry.counter('documented_total')
G = telemetry.counter('ghost_total')

def f():
    with telemetry.trace_span('select'):
        pass
'''
    clean_config = '''
TRAIN_DEFAULTS = {
    'gamma': 0.8,
}

def validate(args):
    ta = args['train_args']
    assert float(ta.get('gamma')) > 0
'''
    clean_params = _PARAM_DOC.replace(
        "| `phantom_knob` | 1 | no longer exists |\n", '')
    assert check_gl005(_gl005_tree(clean_emitter, clean_config,
                                   _OBS_DOC, clean_params)) == []


# GL005 alert-rule vocabulary (telemetry.BUILTIN_ALERTS <-> alert catalog)


_ALERT_SRC = '''
BUILTIN_ALERTS = (
    {'name': 'documented_alert', 'metric': 'documented_total',
     'kind': 'rate', 'op': '>', 'threshold': 0.0},
    {'name': 'undocumented_alert', 'metric': 'documented_total',
     'kind': 'value', 'op': '>', 'threshold': 1.0},
)
'''

_OBS_ALERT_DOC = _OBS_DOC + '''## Alerting and postmortems
### Alert catalog
| alert | meaning |
|---|---|
| `documented_alert` | fires on stall |
| `stale_alert` | rule was deleted |
'''


def test_gl005_alert_vocabulary_both_directions():
    tree = _gl005_tree(obs=_OBS_ALERT_DOC)
    tree['handyrl_tpu/telemetry.py'] = _src('handyrl_tpu/telemetry.py',
                                            _ALERT_SRC)
    blob = ' | '.join(f.message for f in check_gl005(tree))
    assert "'undocumented_alert'" in blob      # rule -> missing catalog row
    assert "'stale_alert'" in blob             # catalog row -> no such rule
    assert "'documented_alert'" not in blob    # matched pair is silent


def test_gl005_alert_clean_twin_passes():
    clean_emitter = '''
from . import telemetry
C = telemetry.counter('documented_total')
G = telemetry.counter('ghost_total')

def f():
    with telemetry.trace_span('select'):
        pass
'''
    clean_config = '''
TRAIN_DEFAULTS = {
    'gamma': 0.8,
}

def validate(args):
    ta = args['train_args']
    assert float(ta.get('gamma')) > 0
'''
    clean_params = _PARAM_DOC.replace(
        "| `phantom_knob` | 1 | no longer exists |\n", '')
    clean_obs = _OBS_DOC + '''## Alerting and postmortems
### Alert catalog
| alert | meaning |
|---|---|
| `documented_alert` | fires on stall |
'''
    clean_alert_src = '''
BUILTIN_ALERTS = (
    {'name': 'documented_alert', 'metric': 'documented_total',
     'kind': 'rate', 'op': '>', 'threshold': 0.0},
)
'''
    tree = _gl005_tree(clean_emitter, clean_config, clean_obs, clean_params)
    tree['handyrl_tpu/telemetry.py'] = _src('handyrl_tpu/telemetry.py',
                                            clean_alert_src)
    assert check_gl005(tree) == []


# ---------------------------------------------------------------------------
# pragmas + baseline


def test_pragma_with_reason_suppresses_without_reason_fails():
    dirty = '''
import random

def a(xs):
    return random.choice(xs)  # graftlint: allow[GL001] draw is cosmetic

def b(xs):
    return random.choice(xs)  # graftlint: allow[GL001]
'''
    src = _src('handyrl_tpu/generation.py', dirty)
    findings = check_gl001(src)
    result = apply_suppressions(findings, {src.path: src}, [])
    assert len(result.suppressed) == 1
    assert len(result.findings) == 1           # reasonless pragma: still live
    assert len(result.pragma_errors) == 1


def test_baseline_matches_by_context_and_goes_stale(tmp_path):
    dirty = 'import random\n\ndef f(xs):\n    return random.choice(xs)\n'
    src = _src('handyrl_tpu/generation.py', dirty)
    findings = check_gl001(src)
    assert findings

    bl = tmp_path / 'baseline.json'
    bl.write_text(json.dumps([
        {'rule': 'GL001', 'path': 'handyrl_tpu/generation.py',
         'context': 'return random.choice(xs)', 'reason': 'grandfathered'},
        {'rule': 'GL001', 'path': 'handyrl_tpu/generation.py',
         'context': 'return random.shuffle(xs)', 'reason': 'gone'},
    ]))
    entries, errors = load_baseline(str(bl))
    assert errors == []
    result = apply_suppressions(findings, {src.path: src}, entries)
    assert result.findings == []
    assert len(result.baselined) == 1
    assert [e.context for e in result.stale_baseline] == \
        ['return random.shuffle(xs)']


def test_baseline_entry_without_reason_is_a_config_error(tmp_path):
    bl = tmp_path / 'baseline.json'
    bl.write_text(json.dumps([
        {'rule': 'GL001', 'path': 'x.py', 'context': 'y', 'reason': ''}]))
    entries, errors = load_baseline(str(bl))
    assert entries == []
    assert errors and 'missing reason' in errors[0]


def test_placeholder_pragma_reason_flagged_for_strict():
    """A pragma whose reason is still the --write-baseline scaffold
    placeholder suppresses the finding (non-strict stays green) but is
    reported in placeholder_reasons — the --strict CI gate fails it until
    a human justifies the exemption."""
    from handyrl_tpu.analysis.core import PLACEHOLDER_REASON
    dirty = '''
import random

def f(xs):
    return random.choice(xs)  # graftlint: allow[GL001] %s
''' % PLACEHOLDER_REASON
    src = _src('handyrl_tpu/generation.py', dirty)
    result = apply_suppressions(check_gl001(src), {src.path: src}, [])
    assert result.findings == [] and result.pragma_errors == []
    assert len(result.suppressed) == 1
    assert len(result.placeholder_reasons) == 1
    assert 'scaffold placeholder' in result.placeholder_reasons[0]


def test_placeholder_baseline_reason_flagged_for_strict(tmp_path):
    from handyrl_tpu.analysis.core import PLACEHOLDER_REASON
    dirty = 'import random\n\ndef f(xs):\n    return random.choice(xs)\n'
    src = _src('handyrl_tpu/generation.py', dirty)
    findings = check_gl001(src)
    bl = tmp_path / 'baseline.json'
    bl.write_text(json.dumps([
        {'rule': 'GL001', 'path': 'handyrl_tpu/generation.py',
         'context': 'return random.choice(xs)',
         'reason': PLACEHOLDER_REASON}]))
    entries, errors = load_baseline(str(bl))
    assert errors == []                        # a reason IS present…
    result = apply_suppressions(findings, {src.path: src}, entries)
    assert result.findings == []               # …and it still suppresses
    assert len(result.baselined) == 1
    assert len(result.placeholder_reasons) == 1   # …but strict fails it
    assert 'scaffold placeholder' in result.placeholder_reasons[0]
    # an UNUSED placeholder entry is stale, not placeholder-flagged twice
    result2 = apply_suppressions([], {src.path: src}, entries)
    assert result2.placeholder_reasons == []
    assert len(result2.stale_baseline) == 1


# ---------------------------------------------------------------------------
# the CI gate on the real tree


def test_repo_is_strict_clean():
    result = run_lint()
    live = [f.render() for f in result.findings + result.pragma_errors]
    assert live == [], '\n'.join(live)
    assert [e.context for e in result.stale_baseline] == []
    assert result.config_errors == []
    # every grandfathered entry carries a non-trivial written reason
    entries, _ = load_baseline(
        os.path.join(repo_root(), '.graftlint-baseline.json'))
    assert entries, 'expected grandfathered GL001 entries'
    for e in entries:
        assert len(e.reason) > 20, e


def test_cli_strict_exits_zero():
    proc = subprocess.run(
        [sys.executable, '-m', 'handyrl_tpu.analysis', '--strict'],
        cwd=repo_root(), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert '0 finding(s)' in proc.stdout


# ---------------------------------------------------------------------------
# runtime sanitizer


@pytest.fixture
def sanitized():
    sz.install()
    sz.reset()
    try:
        yield sz
    finally:
        sz.uninstall()
        sz.reset()


@pytest.mark.timeout(60)
def test_sanitizer_detects_abba_without_hanging(sanitized):
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def ab():
        with lock_a:
            with lock_b:
                pass

    def ba():
        with lock_b:
            with lock_a:
                pass

    # sequential opposite-order acquisitions: the ABBA pattern is recorded
    # from the edge graph alone — no actual deadlock is ever risked
    for fn, name in ((ab, 'order-ab'), (ba, 'order-ba')):
        t = threading.Thread(target=fn, name=name)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive()
    report = sanitized.lock_report()
    assert len(report['inversions']) == 1
    with pytest.raises(AssertionError, match='lock-order inversion'):
        sanitized.assert_clean()


@pytest.mark.timeout(60)
def test_sanitizer_consistent_order_is_clean(sanitized):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert sanitized.lock_report()['inversions'] == []
    sanitized.assert_clean()


@pytest.mark.timeout(60)
def test_sanitizer_condition_wait_makes_no_phantom_edges(sanitized):
    cv = threading.Condition()
    other = threading.Lock()
    woke = []

    def waiter():
        with cv:
            cv.wait(timeout=10)
            woke.append(1)

    t = threading.Thread(target=waiter, name='cv-waiter')
    t.start()
    time.sleep(0.2)
    with other:            # acquired while the waiter SLEEPS inside cv.wait
        pass               # — must not read as nested under the cv lock
    with cv:
        cv.notify_all()
    t.join(timeout=30)
    assert woke == [1]
    assert sanitized.lock_report()['inversions'] == []


@pytest.mark.timeout(60)
def test_sanitizer_rlock_reentrancy_adds_no_self_edges(sanitized):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert sanitized.lock_report()['edges'] == 0


@pytest.mark.timeout(60)
def test_thread_accountant_flags_unnamed_and_leaks(sanitized):
    gate = threading.Event()
    t = threading.Thread(target=gate.wait)      # anonymous, non-daemon
    t.start()
    report = sanitized.thread_report()
    assert len(report['unnamed']) == 1
    assert len(report['leaked']) == 1
    with pytest.raises(AssertionError, match='leaked non-daemon thread'):
        sanitized.assert_clean()
    gate.set()
    t.join(timeout=30)
    assert sanitized.thread_report()['leaked'] == []


@pytest.mark.timeout(120)
def test_sanitizer_env_install_reports_at_exit(tmp_path):
    """HANDYRL_TPU_SANITIZE=1 installs at package import and prints the
    one-line report at exit — the wiring the chaos CI legs rely on."""
    code = ('import threading\n'
            'import handyrl_tpu\n'
            'a = threading.Lock()\n'
            'b = threading.Lock()\n'
            'with a:\n'
            '    with b: pass\n'
            'with b:\n'
            '    with a: pass\n')
    env = dict(os.environ, HANDYRL_TPU_SANITIZE='1', JAX_PLATFORMS='cpu')
    proc = subprocess.run([sys.executable, '-c', code], env=env,
                          capture_output=True, text=True, timeout=90)
    assert proc.returncode == 0, proc.stderr
    assert 'graftlint-sanitizer: 1 lock-order inversion(s)' in proc.stderr
