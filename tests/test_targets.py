"""Golden tests for the target algorithms.

Each scan implementation is checked against a slow, obviously-correct numpy
loop oracle written directly from the recursions, plus hand-computed
mini-sequences and structural properties (mask collapse, MC fallback).
"""

import numpy as np
import pytest

from handyrl_tpu.ops.targets import compute_target

B, T, P = 3, 7, 2
SHAPE = (B, T, P, 1)


def _rand(seed):
    rng = np.random.RandomState(seed)
    values = rng.randn(*SHAPE).astype(np.float32)
    returns = rng.randn(*SHAPE).astype(np.float32)
    rewards = rng.randn(*SHAPE).astype(np.float32)
    rhos = rng.uniform(0.1, 1.0, SHAPE).astype(np.float32)
    cs = rng.uniform(0.1, 1.0, SHAPE).astype(np.float32)
    masks = (rng.rand(*SHAPE) > 0.3).astype(np.float32)
    return values, returns, rewards, rhos, cs, masks


# ---- numpy loop oracles (independent re-derivation of the recursions) ----

def np_lambda(lmb, masks):
    return lmb + (1 - lmb) * (1 - masks)


def np_td(values, returns, rewards, lambda_, gamma):
    tv = np.zeros_like(values)
    tv[:, -1] = returns[:, -1]
    for t in range(T - 2, -1, -1):
        r = rewards[:, t] if rewards is not None else 0
        lam = lambda_[:, t + 1]
        tv[:, t] = r + gamma * ((1 - lam) * values[:, t + 1] + lam * tv[:, t + 1])
    return tv, tv - values


def np_upgo(values, returns, rewards, lambda_, gamma):
    tv = np.zeros_like(values)
    tv[:, -1] = returns[:, -1]
    for t in range(T - 2, -1, -1):
        r = rewards[:, t] if rewards is not None else 0
        lam = lambda_[:, t + 1]
        mixed = (1 - lam) * values[:, t + 1] + lam * tv[:, t + 1]
        tv[:, t] = r + gamma * np.maximum(values[:, t + 1], mixed)
    return tv, tv - values


def np_vtrace(values, returns, rewards, lambda_, gamma, rhos, cs):
    rew = rewards if rewards is not None else np.zeros_like(values)
    v_next = np.concatenate([values[:, 1:], returns[:, -1:]], axis=1)
    deltas = rhos * (rew + gamma * v_next - values)
    vmv = np.zeros_like(values)
    vmv[:, -1] = deltas[:, -1]
    for t in range(T - 2, -1, -1):
        vmv[:, t] = deltas[:, t] + gamma * lambda_[:, t + 1] * cs[:, t] * vmv[:, t + 1]
    vs = vmv + values
    vs_next = np.concatenate([vs[:, 1:], returns[:, -1:]], axis=1)
    adv = rew + gamma * vs_next - values
    return vs, adv


@pytest.mark.parametrize('algorithm', ['TD', 'UPGO', 'VTRACE'])
@pytest.mark.parametrize('gamma', [1.0, 0.8])
@pytest.mark.parametrize('use_rewards', [True, False])
def test_targets_match_loop_oracle(algorithm, gamma, use_rewards):
    values, returns, rewards, rhos, cs, masks = _rand(42)
    rew = rewards if use_rewards else None
    lmb = 0.7
    got_t, got_a = compute_target(algorithm, values, returns, rew, lmb, gamma, rhos, cs, masks)

    lambda_ = np_lambda(lmb, masks)
    oracle = {'TD': np_td, 'UPGO': np_upgo}.get(algorithm)
    if oracle is not None:
        want_t, want_a = oracle(values, returns, rew, lambda_, gamma)
    else:
        want_t, want_a = np_vtrace(values, returns, rew, lambda_, gamma, rhos, cs)

    np.testing.assert_allclose(np.asarray(got_t), want_t, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_a), want_a, rtol=1e-5, atol=1e-5)


def test_monte_carlo():
    values, returns, *_ , rhos, cs, masks = _rand(1)
    t, a = compute_target('MC', values, returns, None, 0.7, 1.0, rhos, cs, masks)
    np.testing.assert_allclose(np.asarray(t), returns)
    np.testing.assert_allclose(np.asarray(a), returns - values, rtol=1e-6)


def test_no_baseline_falls_back_to_returns():
    _, returns, _, rhos, cs, masks = _rand(2)
    t, a = compute_target('TD', None, returns, None, 0.7, 1.0, rhos, cs, masks)
    np.testing.assert_allclose(np.asarray(t), returns)
    np.testing.assert_allclose(np.asarray(a), returns)


def test_td_hand_computed_two_steps():
    """Tiny hand-derived case: B=1, T=2, P=1, full mask.
    tv_1 = G_1; tv_0 = r_0 + g*((1-l)*V_1 + l*tv_1)."""
    values = np.array([0.5, 0.25], np.float32).reshape(1, 2, 1, 1)
    returns = np.array([0.9, 1.0], np.float32).reshape(1, 2, 1, 1)
    rewards = np.array([0.1, 0.0], np.float32).reshape(1, 2, 1, 1)
    ones = np.ones((1, 2, 1, 1), np.float32)
    g, l = 0.9, 0.7
    t, _ = compute_target('TD', values, returns, rewards, l, g, ones, ones, ones)
    tv1 = 1.0
    tv0 = 0.1 + g * ((1 - l) * 0.25 + l * tv1)
    np.testing.assert_allclose(np.asarray(t).ravel(), [tv0, tv1], rtol=1e-6)


def test_masked_steps_collapse_to_lambda_one():
    """With mask=0 everywhere, lambda=1: TD target becomes the discounted
    reward-sum bootstrapped from the final return (pure MC-style rollup)."""
    values, returns, rewards, rhos, cs, _ = _rand(3)
    zeros = np.zeros(SHAPE, np.float32)
    g = 0.8
    t, _ = compute_target('TD', values, returns, rewards, 0.3, g, rhos, cs, zeros)
    want = np.zeros_like(values)
    want[:, -1] = returns[:, -1]
    for i in range(T - 2, -1, -1):
        want[:, i] = rewards[:, i] + g * want[:, i + 1]
    np.testing.assert_allclose(np.asarray(t), want, rtol=1e-5, atol=1e-5)


def test_vtrace_hand_computed_two_steps():
    """Fully hand-derived V-Trace case (B=1, T=2, P=1, full mask):
    d0 = rho0*(r0 + g*v1 - v0), d1 = rho1*(r1 + g*G - v1),
    vs = v + [d0 + g*l*c0*d1, d1], adv = r + g*[vs1, G] - v."""
    def arr(*vals):
        return np.array(vals, np.float32).reshape(1, 2, 1, 1)

    values, returns = arr(0.5, 0.25), arr(0.0, 1.0)
    rewards, rhos, cs = arr(0.1, 0.2), arr(0.8, 0.9), arr(0.7, 0.6)
    ones = np.ones((1, 2, 1, 1), np.float32)
    g, l = 0.9, 0.6
    vs, adv = compute_target('VTRACE', values, returns, rewards, l, g, rhos, cs, ones)

    d0 = 0.8 * (0.1 + g * 0.25 - 0.5)            # -0.14
    d1 = 0.9 * (0.2 + g * 1.0 - 0.25)            # 0.765
    vmv0 = d0 + g * l * 0.7 * d1                 # 0.14917
    want_vs = [0.5 + vmv0, 0.25 + d1]
    want_adv = [0.1 + g * want_vs[1] - 0.5, 0.2 + g * 1.0 - 0.25]
    np.testing.assert_allclose(np.asarray(vs).ravel(), want_vs, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(adv).ravel(), want_adv, rtol=1e-5)
