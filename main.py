#!/usr/bin/env python
"""handyrl_tpu CLI: train / train-server / worker / eval / eval-server /
eval-client, mirroring the reference's six modes (main.py:19-38)."""

import sys

from handyrl_tpu.config import load_config

USAGE = """usage: python main.py MODE [args]
modes:
  --train, -t          stand-alone training on this host
  --train-server, -ts  training server awaiting remote workers
  --worker, -w         worker host feeding a training server [num_parallel]
  --eval, -e           evaluate MODEL_PATH[:OPPONENT] [NUM_GAMES [NUM_PROC]]
  --eval-server, -es   network battle server [NUM_GAMES [NUM_PROC]]
  --eval-client, -ec   network battle client MODEL_PATH [HOST]
  --serve, -sv         standalone model-serving tier (registry-versioned
                       inference service; SIGTERM drains and exits 75)
  --serve-fleet, -sf   replicated serving fleet: resolver/router +
                       serving.fleet.replicas managed replicas (SLO-driven
                       autoscaling, zero-loss failover, rolling promotes)
  --gateway, -gw       match gateway over a serving fleet: server-held
                       game sessions (open/play/close), drain handoff +
                       journal-replay reconstruction, outcomes -> RatingBook
  --status             render a live /statusz health view [HOST:PORT]
                       (active alerts, fleet states, progress, recorder)
"""


def main():
    import os
    if os.environ.get('JAX_PLATFORMS', '').strip() == 'cpu':
        # the axon TPU site hook overrides the env var via jax config at
        # import; honor an explicit CPU request anyway
        import jax
        jax.config.update('jax_platforms', 'cpu')

    from handyrl_tpu import setup_compile_cache
    setup_compile_cache()

    args = load_config('config.yaml')
    print(args)

    if len(sys.argv) < 2:
        print(USAGE)
        sys.exit(1)

    mode = sys.argv[1]
    rest = sys.argv[2:]

    if mode in ('--train', '-t'):
        from handyrl_tpu.train import train_main
        train_main(args)
    elif mode in ('--train-server', '-ts'):
        from handyrl_tpu.train import train_server_main
        train_server_main(args)
    elif mode in ('--worker', '-w'):
        from handyrl_tpu.worker import worker_main
        worker_main(args, rest)
    elif mode in ('--eval', '-e'):
        from handyrl_tpu.evaluation import eval_main
        eval_main(args, rest)
    elif mode in ('--eval-server', '-es'):
        from handyrl_tpu.evaluation import eval_server_main
        eval_server_main(args, rest)
    elif mode in ('--eval-client', '-ec'):
        from handyrl_tpu.evaluation import eval_client_main
        eval_client_main(args, rest)
    elif mode in ('--serve', '-sv'):
        from handyrl_tpu.serving.service import serve_main
        serve_main(args, rest)
    elif mode in ('--serve-fleet', '-sf'):
        from handyrl_tpu.serving.fleet import resolver_main
        resolver_main(args, rest)
    elif mode in ('--gateway', '-gw'):
        from handyrl_tpu.serving.gateway import gateway_main
        gateway_main(args, rest)
    elif mode == '--status':
        from handyrl_tpu.telemetry import status_main
        status_main(args.get('train_args'), rest)
    else:
        print('Not found mode %s.' % mode)
        print(USAGE)


if __name__ == '__main__':
    main()
