"""Headline benchmark: learner trajectories/sec on the flagship config.

Measures the full compiled update step (forward + targets + losses + grads +
Adam) on GeeseNet at the reference's default batch geometry (batch 128 x
forward_steps 16, config.yaml:12-18), on the default JAX device (the TPU
chip under the driver). ``vs_baseline`` is measured-ours / measured-reference:
the denominator comes from bench_baseline.json, produced by
scripts/baseline_torch_learner.py — the same step in PyTorch on this host's
CPU (the reference publishes no numbers of its own; see BASELINE.md).

Robustness contract (round-2 hardening):
  * exactly ONE JSON line is printed on stdout in every outcome — success,
    backend unavailable, timeout, or signal — and the process exits 0;
  * the backend is probed in a SUBPROCESS with a short deadline, so a wedged
    TPU tunnel cannot hang this process (round 1 lost its whole driver
    timeout to a blocking in-process ``jax.devices()`` retry loop);
  * a global SIGALRM deadline (BENCH_DEADLINE_SEC, default 600) bounds the
    whole run; SIGTERM/SIGINT emit the JSON line before exiting — children
    are terminated politely (SIGTERM, never SIGKILL) so an axon client
    holding the exclusive tunnel grant always gets to release it.

Success line also carries diagnostics (extra keys are additive):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "device": ..., "flops_per_step": N, "mfu": N}
"""

import json
import os
import signal
import subprocess
import sys
import time

_EMITTED = False
_CHILDREN = []

METRIC = 'learner trajectories/sec (GeeseNet B=128 T=16, full update step)'
UNIT = 'trajectories/sec'

# BENCH_MODE=ingest measures the HOST side of the distributed learner path
# instead: batches/sec from buffered episodes through the Batcher
# (select -> bz2 decode -> arena assembly) to a staged, transfer-complete
# device buffer. vs_baseline divides by the SAME pipeline running the
# pre-vectorization reference builder (ops/batch.py make_batch_reference).
INGEST_METRIC = ('host ingest batches/sec (GeeseNet B=128 T=16, '
                 'Batcher -> staged device buffer)')
INGEST_UNIT = 'batches/sec'

# BENCH_MODE=actor measures the distributed ACTOR data path: fleet
# episodes/sec through a real gather + worker-process subtree speaking the
# 4-RPC protocol, with the per-host batched InferenceEngine enabled
# (inference.py) vs the per-worker B=1 reference path — identical seeds,
# identical task stream, byte-compared episode records. vs_baseline is
# engine-eps / per-worker-eps measured by the SAME harness.
ACTOR_METRIC = ('fleet episodes/sec (HungryGeese/GeeseNet, gather+workers '
                'over the 4-RPC protocol, engine-batched inference vs '
                'per-worker B=1)')
ACTOR_UNIT = 'episodes/sec'

# BENCH_MODE=serve measures the standalone model-serving tier: sustained
# requests/sec and tail latency (client-side p50/p95/p99) of a real
# InferenceService subprocess (registry-resolved models, framed INFER
# protocol over TCP, continuous batching) under a synthetic many-client
# load, plus a measured graceful drain: a final wave of in-flight requests
# is answered through a SIGTERM (no request dropped un-answered, exit 75).
# vs_baseline is many-client req/s over single-client req/s measured by the
# SAME harness — the continuous-batching concurrency gain.
SERVE_METRIC = ('service requests/sec (standalone InferenceService, '
                'registry-resolved models, framed INFER protocol over TCP, '
                'synthetic many-client load)')
SERVE_UNIT = 'requests/sec'

# BENCH_MODE=gateway measures the match-gateway session tier: completed
# matches/sec through a real gateway subprocess over a real 2-replica
# fleet (server-held sessions, opponent seats stepped through the fleet,
# one round trip per client ply), with a mid-run replica SIGKILL — the
# row must show ZERO dropped sessions (stranded sessions are rebuilt by
# journal replay). vs_baseline is N-session matches/sec over
# single-session matches/sec measured by the SAME harness — the session
# concurrency gain.
GATEWAY_METRIC = ('gateway matches/sec (MatchGateway over a replicated '
                  'fleet, server-held sessions, mid-run replica SIGKILL '
                  'with journal-replay reconstruction)')
GATEWAY_UNIT = 'matches/sec'

# BENCH_MODE=mesh measures the mesh-sharded learner: SGD steps/sec of the
# partition-rule-built NamedSharding/jit update step at 1/2/4/8 devices
# (one subprocess per mesh size — the virtual-device count is fixed before
# jax import). Each row carries BOTH the wall-clock rate of the sharded
# program on this host's (possibly virtual) mesh AND the per-shard
# strong-scaling projection: the single-device rate at batch B/ndev, i.e.
# what each device of a real ndev-mesh computes per step. On a
# one-core CI host the virtual mesh time-slices its devices, so the
# projection (plus the measured cross-mesh loss parity) carries the
# scaling claim; on real silicon the wall clock does.
MESH_METRIC = ('sharded learner SGD steps/sec (GeeseNet B=128 T=16, '
               'partition-rule NamedSharding jit over the data mesh)')
MESH_UNIT = 'steps/sec'

# Per-chip peaks by device_kind substring: (key, bf16 FLOP/s, HBM bytes/s).
# Public figures: v4 275T & 1.23TB/s, v5e 197T & 819GB/s, v5p 459T &
# 2.77TB/s, v6e 918T & 1.64TB/s.
_PEAKS = (
    ('v6', 918e12, 1.64e12),
    ('v5p', 459e12, 2.77e12),
    ('v5 lite', 197e12, 819e9),
    ('v5e', 197e12, 819e9),
    ('v4', 275e12, 1.23e12),
    ('v3', 123e12, 900e9),
    ('v2', 45e12, 700e9),
)


def _peak(device_kind: str, column: int) -> float:
    kind = device_kind.lower()
    for row in _PEAKS:
        if row[0] in kind:
            return row[column]
    return 0.0


def _active_mode() -> str:
    return os.environ.get('BENCH_MODE', 'headline').strip().lower()


def _git_sha() -> str:
    """The repo HEAD sha stamped on every row, so the benchmarks.jsonl
    trajectory can be diffed across commits ('' outside a git checkout)."""
    try:
        out = subprocess.run(
            ['git', 'rev-parse', 'HEAD'], capture_output=True, text=True,
            timeout=10, cwd=os.path.dirname(os.path.abspath(__file__)))
        return out.stdout.strip() if out.returncode == 0 else ''
    except Exception:
        return ''


# bump when the emitted row shape changes incompatibly (keys renamed or
# re-typed) — consumers filter rows by this before diffing trajectories
BENCH_SCHEMA_VERSION = 2


def emit(value=0.0, vs_baseline=0.0, **extra):
    """Print the one JSON result line (at most once) and flush."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    metric, unit = {'ingest': (INGEST_METRIC, INGEST_UNIT),
                    'actor': (ACTOR_METRIC, ACTOR_UNIT),
                    'mesh': (MESH_METRIC, MESH_UNIT),
                    'serve': (SERVE_METRIC, SERVE_UNIT),
                    'gateway': (GATEWAY_METRIC, GATEWAY_UNIT)}.get(
                        _active_mode(), (METRIC, UNIT))
    line = {'metric': metric, 'value': round(float(value), 2), 'unit': unit,
            'vs_baseline': round(float(vs_baseline), 2),
            'git_sha': _git_sha(), 'schema_version': BENCH_SCHEMA_VERSION}
    line.update(extra)
    # silent-fallback guard (ROADMAP "Recent"): every row records what
    # backend the operator asked for vs what the run actually landed on,
    # and an explicit request that fell back (tpu -> cpu) marks the row
    # degraded so perf_gate.py and humans never diff it against real silicon
    requested = (os.environ.get('BENCH_BACKEND')
                 or os.environ.get('JAX_PLATFORMS')
                 or 'auto').split(',')[0].strip().lower()
    actual = str(line.get('backend', 'unknown')).lower()
    line.setdefault('backend_requested', requested)
    line.setdefault('backend_actual', actual)
    if (requested not in ('', 'auto') and actual != 'unknown'
            and actual != requested):
        line['degraded'] = True
        print('WARNING: bench requested backend %r but ran on %r — row '
              'marked degraded' % (requested, actual),
              file=sys.stderr, flush=True)
    print(json.dumps(line), flush=True)


def _shutdown(signum, _frame):
    for proc in _CHILDREN:
        if proc.poll() is None:
            proc.terminate()  # SIGTERM only: let axon clients drop the grant
    emit(error='interrupted by signal %d before a number was measured' % signum)
    sys.exit(0)


def probe_backend(deadline: float) -> dict:
    """Ask a subprocess what backend/device is reachable, under a hard cap.

    Returns {'backend': ..., 'device_kind': ...} or {'error': ...}. The
    subprocess is the fail-fast layer: if backend init blocks on a wedged
    tunnel we SIGTERM it and report unavailable instead of hanging.
    """
    code = (
        "import json, os, jax\n"
        # honor an explicit operator platform choice: the axon site hook
        # overrides JAX_PLATFORMS at import, so re-assert it via config
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "jax.config.update('jax_platforms', p) if p else None\n"
        "d = jax.devices()[0]\n"
        "print(json.dumps({'backend': jax.default_backend(),"
        " 'device_kind': d.device_kind, 'n': jax.device_count()}))\n"
    )
    proc = subprocess.Popen([sys.executable, '-c', code],
                            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                            text=True)
    _CHILDREN.append(proc)
    try:
        out, _ = proc.communicate(timeout=deadline)
        if proc.returncode == 0 and out.strip():
            return json.loads(out.strip().splitlines()[-1])
        return {'error': 'probe exited rc=%s' % proc.returncode}
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # leave it to die with us; do not SIGKILL a grant holder
        return {'error': 'backend init exceeded %.0fs fail-fast deadline'
                         % deadline}


def peak_flops(device_kind: str) -> float:
    return _peak(device_kind, 1)


def peak_hbm_bw(device_kind: str) -> float:
    return _peak(device_kind, 2)


def time_compiled_step(step_fn, state, batch, lr, steps, warmup=3,
                       chunk=5):
    """AOT-compile ``step_fn`` and time ``steps`` executions.

    Two measurement-integrity rules, both learned on the axon TPU tunnel:

    * The batch is materialized on device FIRST so the timed loop measures
      compute, not per-step host-to-device transfer (``jnp.asarray`` is a
      no-op for arrays already on device, so pre-sharded batches keep
      their shardings).
    * Dispatch is CHUNKED with a hard host-side sync (a scalar fetched to
      numpy) after every ``chunk`` steps. ``block_until_ready`` alone can
      resolve before remote execution has drained on tunneled backends —
      we measured a "step time" 100x faster than the chip's peak FLOP/s
      allows — and unbounded async queueing can wedge the tunnel outright.
      Fetching a value that data-depends on every queued step closes both
      holes; with chunk=5 the added round-trip latency is amortized to
      noise.

    Returns (seconds_per_step, flops_per_step, hbm_bytes_per_step); the
    flop and byte counts come from XLA's own cost analysis of the same
    executable, both 0.0 if the AOT path is unavailable.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    batch = jax.tree_util.tree_map(jnp.asarray, batch)
    flops = hbm_bytes = 0.0
    try:
        compiled = step_fn.lower(state, batch, lr).compile()
    except Exception:
        compiled = step_fn   # jitted callable; flops stay unreported
    else:
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float((cost or {}).get('flops', 0.0))
            hbm_bytes = float((cost or {}).get('bytes accessed', 0.0))
        except Exception:
            pass   # keep the valid executable; flops stay unreported

    def sync(metrics):
        # a host fetch of a scalar that depends on the whole chain is the
        # only sync we trust on a tunneled backend
        return float(np.asarray(metrics['total']))

    for _ in range(max(1, warmup)):   # >=1: 'metrics' must be bound
        state, metrics = compiled(state, batch, lr)
    sync(metrics)
    done = 0
    t0 = time.time()
    while done < steps:
        n = min(chunk, steps - done)
        for _ in range(n):
            state, metrics = compiled(state, batch, lr)
        sync(metrics)
        done += n
    return (time.time() - t0) / steps, flops, hbm_bytes


def headline_setup(B=128, T=16, dtype=None, seed=0, torus_impl=None):
    """Build the headline-config pieces: (module, cfg, batch, state).

    The ONE definition of what the headline benchmark measures — GeeseNet
    at the reference's default geometry with TD/TD targets. Shared by
    run_bench and scripts/tpu_scaling_bench.py so the scaling sweep always
    measures the same program as the headline number it explains.
    ``dtype`` (e.g. jnp.bfloat16) clones the net with reduced-precision
    activations; params stay float32 (the learner's compute_dtype mode).
    ``torus_impl`` ('pad'/'halo') selects the TorusConv implementation
    (identical function, different HBM behavior — models/blocks.py).
    """
    import jax
    import numpy as np

    import handyrl_tpu
    handyrl_tpu.setup_compile_cache()
    from handyrl_tpu.models import build
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import init_train_state
    from __graft_entry__ import _synthetic_batch

    module = build('GeeseNet')
    if dtype is not None:
        module = module.clone(dtype=dtype)
    if torus_impl is not None:
        module = module.clone(torus_impl=torus_impl)
    rng = np.random.RandomState(seed)
    batch = _synthetic_batch(B, T, 1, (17, 7, 11), 4, rng)
    params = module.init(jax.random.PRNGKey(0),
                         batch['observation'][:, 0, 0], None)
    state = init_train_state(params)
    cfg = LossConfig(turn_based_training=False, observation=True,
                     policy_target='TD', value_target='TD', gamma=0.99)
    return module, cfg, batch, state


def run_bench(probe: dict):
    import jax
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    import jax.numpy as jnp

    from handyrl_tpu.ops.train_step import build_update_step
    from handyrl_tpu.parallel.mesh import make_mesh, shard_batch

    B, T = 128, 16
    steps = 30

    # bf16 activations on the MXU (the learner's compute_dtype mode,
    # tests/test_bf16.py); params and the optimizer stay float32
    module, cfg, batch, state = headline_setup(B, T, dtype=jnp.bfloat16)
    devices = jax.devices()
    mesh = make_mesh(devices) if len(devices) > 1 else None
    step = build_update_step(module, cfg, mesh=mesh, donate=False)
    if mesh is not None:
        batch = shard_batch(mesh, batch)
    lr = jnp.asarray(1e-5, jnp.float32)

    sec_per_step, flops_per_step, hbm_bytes_per_step = time_compiled_step(
        step, state, batch, lr, steps)
    dt = sec_per_step * steps
    traj_per_sec = B / sec_per_step

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'bench_baseline.json')
    vs_baseline = 0.0
    baseline_def = 'no baseline file'
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
        # we measure in bf16; divide by the FASTER of the torch fp32/bf16
        # rows so the ratio never flatters a dtype mismatch
        fp32 = base.get('torch_cpu_trajectories_per_sec', 0.0)
        bf16 = base.get('torch_cpu_bf16_trajectories_per_sec', 0.0)
        ref = max(fp32, bf16)
        if ref > 0:
            vs_baseline = traj_per_sec / ref
            baseline_def = ('ours-bf16 / torch-cpu-%s (best of fp32 %.1f, '
                            'bf16-autocast %.1f traj/s)'
                            % ('bf16' if bf16 >= fp32 else 'fp32',
                               fp32, bf16))
        else:
            baseline_def = 'baseline file present but has no usable rows'

    # cost_analysis covers the whole (possibly sharded) program, so the
    # denominator is the peak of every device it ran across
    peak = peak_flops(probe.get('device_kind', '')) * max(1, len(devices))
    mfu = (flops_per_step * steps / dt / peak) if peak else 0.0
    # roofline: which wall does the step actually sit against? mbu is the
    # fraction of peak HBM bandwidth the measured step sustains; whichever
    # utilization is higher names the bound
    bw = peak_hbm_bw(probe.get('device_kind', '')) * max(1, len(devices))
    mbu = (hbm_bytes_per_step / sec_per_step / bw) if bw else 0.0
    bound = ('hbm' if mbu >= mfu else 'mxu') if (mbu or mfu) else 'unknown'
    emit(traj_per_sec, vs_baseline,
         device=probe.get('device_kind', 'unknown'),
         backend=probe.get('backend', 'unknown'),
         step_ms=round(dt / steps * 1e3, 2),
         flops_per_step=flops_per_step,
         hbm_bytes_per_step=hbm_bytes_per_step,
         compute_dtype='bfloat16', vs_baseline_def=baseline_def,
         mfu=round(mfu, 4), mbu=round(mbu, 4), roofline_bound=bound)


def _synthetic_geese_episodes(n_eps, rng, compress_steps=4, num_players=4,
                              min_steps=24, max_steps=96):
    """Buffered-episode stand-ins at the HungryGeese record geometry:
    (17, 7, 11) float32 observation planes per player per ply, 4 actions,
    all seats acting every ply (simultaneous env, solo-training config).
    Planes are sparse binary like real goose boards, so bz2 block sizes —
    and therefore the decode stage this benchmark times — are realistic
    rather than incompressible white noise."""
    from handyrl_tpu.ops.batch import compress_moments
    import numpy as np

    players = list(range(num_players))
    eps = []
    for _ in range(n_eps):
        steps = int(rng.randint(min_steps, max_steps + 1))
        moments = []
        for _t in range(steps):
            moments.append({
                'observation': {p: (rng.rand(17, 7, 11) < 0.08)
                                .astype(np.float32) for p in players},
                'selected_prob': {p: float(rng.rand()) for p in players},
                'action_mask': {p: np.zeros(4, np.float32) for p in players},
                'action': {p: int(rng.randint(4)) for p in players},
                'value': {p: np.array([float(rng.rand())], np.float32)
                          for p in players},
                'reward': {p: 0.0 for p in players},
                'return': {p: float(rng.rand()) - 0.5 for p in players},
                'turn': players,
            })
        eps.append({'args': {'player': players}, 'steps': steps,
                    'outcome': {p: float(np.sign(rng.randn()))
                                for p in players},
                    'moment': compress_moments(moments, compress_steps)})
    return eps


def _measure_ingest(build_fn, episodes, args, n_batches, timer=None):
    """batches/sec through Batcher -> device_put -> transfer complete,
    using the REAL Batcher machinery (same queues, threads, staging)."""
    import jax
    import jax.numpy as jnp
    from collections import deque
    from handyrl_tpu.train import Batcher

    batcher = Batcher(args, deque(episodes), timer=timer, build_fn=build_fn)
    batcher.run()

    def next_batch():
        # with tracing on the thread batcher wraps batches in TracedBatch
        nxt = batcher.batch(timeout=60)
        return nxt.batch if hasattr(nxt, 'trace_ids') else nxt

    nxt = next_batch()               # warmup: thread spin-up, allocators
    dev = jax.tree_util.tree_map(jnp.asarray, nxt)
    jax.block_until_ready(dev)
    t0 = time.time()
    for _ in range(n_batches):
        nxt = next_batch()
        th = time.time()
        dev = jax.tree_util.tree_map(jnp.asarray, nxt)
        jax.block_until_ready(dev)
        if timer is not None:
            timer.add('h2d', time.time() - th)
    dt = time.time() - t0
    batcher.stop()
    return n_batches / max(dt, 1e-9)


def run_ingest(probe: dict):
    """BENCH_MODE=ingest: the host ingest path, CPU-measurable.

    Env knobs (CI smoke shrinks them): BENCH_INGEST_BATCHES (timed batches,
    default 20), BENCH_INGEST_EPISODES (buffer size, default 32),
    BENCH_INGEST_BATCH_SIZE (default 128), BENCH_INGEST_BATCHERS
    (num_batchers, default 2).
    """
    import numpy as np
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu import telemetry
    from handyrl_tpu.ops.batch import make_batch, make_batch_reference
    from handyrl_tpu.utils.timing import StageTimer

    B = int(os.environ.get('BENCH_INGEST_BATCH_SIZE', '128'))
    T = 16
    n_batches = int(os.environ.get('BENCH_INGEST_BATCHES', '20'))
    n_eps = int(os.environ.get('BENCH_INGEST_EPISODES', '32'))
    args = {
        # the north-star geese training geometry (scripts/run_north_star.py)
        'turn_based_training': False, 'observation': True,
        'forward_steps': T, 'burn_in_steps': 0, 'compress_steps': 4,
        'maximum_episodes': 100000, 'batch_size': B,
        'num_batchers': int(os.environ.get('BENCH_INGEST_BATCHERS', '2')),
    }
    rng = np.random.RandomState(7)
    episodes = _synthetic_geese_episodes(n_eps, rng)

    ref_fn = (lambda sel, a, timer=None, cache=None:  # noqa: E731
              make_batch_reference(sel, a))
    timer = StageTimer()
    import contextlib
    import shutil
    import tempfile
    trace_rate = float(os.environ.get('BENCH_TRACE_RATE', '0.1'))
    trace_dir = tempfile.mkdtemp(prefix='bench_trace.')
    with contextlib.redirect_stdout(sys.stderr):
        # batcher-thread startup prints must not break the one-JSON-line
        # stdout contract
        ref_bps = _measure_ingest(ref_fn, episodes, args, n_batches)
        new_bps = _measure_ingest(make_batch, episodes, args, n_batches,
                                  timer=timer)
        # tracing-off vs tracing-on(sampled) pair: the disabled-path cost
        # claim ("near-zero when off") is guarded by the headline value
        # above staying the headline; this third leg measures the SAME
        # pipeline with episode tracing live at the sampled rate so a
        # regression in either path shows up in benchmarks.jsonl
        telemetry.configure_tracing(trace_dir, trace_rate, force=True)
        try:
            traced_bps = _measure_ingest(make_batch, episodes, args,
                                         n_batches)
        finally:
            telemetry.configure_tracing('', None, force=True)
            shutil.rmtree(trace_dir, ignore_errors=True)
        # recorder-on vs recorder-off pair: the flight recorder defaults on
        # (an operator kills it with the rest of the plane via
        # `telemetry: false`); this adjacent A/B toggles ONLY the ring so
        # its append cost is isolated from metric/span cost — both legs run
        # back to back against identical warmed caches
        # alternating long legs, best-of-5 per side: the ring cost is far
        # below the run-to-run noise of a short timed pass (scheduler
        # stalls only ever slow a leg down), so max throughput per side is
        # the robust capability estimate and a one-shot pair would report
        # noise with either sign
        rounds = []
        for _ in range(5):
            on = _measure_ingest(make_batch, episodes, args, n_batches * 5)
            telemetry.set_recorder_enabled(False)
            try:
                off = _measure_ingest(make_batch, episodes, args,
                                      n_batches * 5)
            finally:
                telemetry.set_recorder_enabled(True)
            rounds.append((on, off))
        recorder_on_bps = max(on for on, _ in rounds)
        recorder_off_bps = max(off for _, off in rounds)
        recorder_overhead = (100.0 * (1.0 - recorder_on_bps /
                                      recorder_off_bps)
                             if recorder_off_bps else 0.0)
        # compiled-performance-plane on vs off pair: the armed retrace
        # sentinel plus a per-leg device-memory sample (the plane's whole
        # per-epoch cost) must stay in the noise on the host ingest path
        # (acceptance: <=2%) — same alternating best-of discipline as the
        # recorder pair
        telemetry.install_jax_monitoring()
        pp_rounds = []
        for _ in range(3):
            telemetry.mark_steady_state('bench ingest A/B')
            try:
                telemetry.sample_device_memory()
                pp_on = _measure_ingest(make_batch, episodes, args,
                                        n_batches * 5)
            finally:
                telemetry.clear_steady_state()
            telemetry.configure_perf_plane(False)
            try:
                pp_off = _measure_ingest(make_batch, episodes, args,
                                         n_batches * 5)
            finally:
                telemetry.configure_perf_plane(True)
            pp_rounds.append((pp_on, pp_off))
        perf_plane_on_bps = max(on for on, _ in pp_rounds)
        perf_plane_off_bps = max(off for _, off in pp_rounds)
        perf_plane_overhead = (100.0 * (1.0 - perf_plane_on_bps /
                                        perf_plane_off_bps)
                               if perf_plane_off_bps else 0.0)
        # spool-on vs spool-off pair: the durable plane's episode WAL
        # (spool.EpisodeSpool) rides the ingest hot path — one CRC-framed
        # msgpack record per ADMITTED episode, packed + appended before
        # the episode is counted. An episode is admitted once but sampled
        # into many batches, so the honest coupling spools the full
        # buffer exactly once per measured leg, the admission writes
        # interleaved evenly across the builds that consume them (one
        # append per built batch would bill the WAL len(leg)/n_eps times
        # over). Same alternating best-of-5 discipline, acceptance <= 2%
        # (scripts/perf_gate.py 'bench-ingest')
        import threading
        from handyrl_tpu.connection import pack as conn_pack
        from handyrl_tpu.spool import EpisodeSpool
        spool_root = tempfile.mkdtemp(prefix='bench_spool.')
        spool = EpisodeSpool(spool_root, segment_mb=64, keep_segments=2)
        spool_lock = threading.Lock()   # batcher threads share the WAL
        spool_idx = [0]
        builds_per_leg = n_batches * 5
        append_stride = max(1, builds_per_leg // len(episodes))

        def spooled_build(sel, a, timer=None, cache=None):
            with spool_lock:
                idx = spool_idx[0]
                spool_idx[0] += 1
                if idx % append_stride == 0:
                    ep = episodes[(idx // append_stride) % len(episodes)]
                    spool.append(idx, conn_pack({'idx': idx, 'episode': ep}))
            return make_batch(sel, a, timer=timer, cache=cache)

        sp_rounds = []
        try:
            for _ in range(5):
                sp_on = _measure_ingest(spooled_build, episodes, args,
                                        n_batches * 5)
                sp_off = _measure_ingest(make_batch, episodes, args,
                                         n_batches * 5)
                sp_rounds.append((sp_on, sp_off))
        finally:
            spool.close()
            shutil.rmtree(spool_root, ignore_errors=True)
        spool_on_bps = max(on for on, _ in sp_rounds)
        spool_off_bps = max(off for _, off in sp_rounds)
        spool_overhead = (100.0 * (1.0 - spool_on_bps / spool_off_bps)
                          if spool_off_bps else 0.0)
        # streaming-on vs streaming-off pair: with the `streaming:` block
        # enabled, episodes arrive as fixed-T window chunks and the
        # learner-side ChunkAssembler folds them back together (decode per
        # chunk, finiteness screen, return fill + canonical recompress at
        # completion). In the real learner ALL admission work runs on the
        # SERVER thread, concurrent with the batcher threads — and the
        # whole-episode path is not free there either (feed_episodes
        # guard-screens every upload, a full decode). So both legs model
        # the topology: a feeder thread admits the full buffer exactly
        # once per leg, paced by the build counter — the off-leg screening
        # whole episodes (guard.episode_is_finite, the real admission
        # cost), the on-leg folding the chunked buffer through a fresh
        # assembler — and builds/sec measures the DELTA streaming adds to
        # the shared host (chunk bookkeeping + return fill + canonical
        # recompress; on a multi-core learner the bz2 legs overlap, GIL
        # released). Worker-side chunking is prepared untimed (that cost
        # lives on the generation host). Same alternating best-of-5
        # discipline, acceptance <= 2% (`chunk_overhead_pct` in
        # scripts/perf_gate.py 'bench-ingest')
        from handyrl_tpu import guard as guard_mod
        from handyrl_tpu.generation import build_chunk
        from handyrl_tpu.ops.batch import decompress_moments
        from handyrl_tpu.streaming import ChunkAssembler
        stream_args = dict(args)
        stream_args.update(
            gamma=0.8,
            streaming={'enabled': True, 'chunk_steps': 32})
        all_chunks = []
        for i, ep in enumerate(episodes):
            moments = decompress_moments(ep['moment'])
            for m in moments:
                m['return'] = {p: None for p in m['return']}
            gen_args = dict(ep['args'], sample_key=i, task_id=i)
            cs = 32
            for ci, base in enumerate(range(0, len(moments), cs)):
                window = moments[base:base + cs]
                final = base + cs >= len(moments)
                all_chunks.append(build_chunk(
                    gen_args, ci, base, window, stream_args,
                    final=final, outcome=ep['outcome'] if final else None))

        def paced_leg(units, admit):
            """One measured leg with a feeder thread admitting ``units``
            once, spread evenly across the leg's builds (the server-thread
            topology). Returns the measured builds/sec."""
            stride = max(1, builds_per_leg // len(units))
            built = [0]
            cond = threading.Condition()

            def feeder():
                for i, unit in enumerate(units):
                    with cond:
                        while built[0] < i * stride:
                            if not cond.wait(timeout=30.0):
                                return     # leg abandoned
                    admit(unit)

            feeder_th = threading.Thread(target=feeder, daemon=True)
            feeder_th.start()

            def paced_build(sel, a, timer=None, cache=None):
                with cond:
                    built[0] += 1
                    cond.notify_all()
                return make_batch(sel, a, timer=timer, cache=cache)

            bps = _measure_ingest(paced_build, episodes, args,
                                  n_batches * 5)
            with cond:
                built[0] += builds_per_leg     # release any waiting folds
                cond.notify_all()
            feeder_th.join(timeout=60)
            return bps

        st_rounds = []
        for _ in range(5):
            asm = ChunkAssembler(stream_args)
            st_on = paced_leg(all_chunks, asm.add)
            st_off = paced_leg(episodes, guard_mod.episode_is_finite)
            st_rounds.append((st_on, st_off))
        streaming_on_bps = max(on for on, _ in st_rounds)
        streaming_off_bps = max(off for _, off in st_rounds)
        chunk_overhead = (100.0 * (1.0 - streaming_on_bps /
                                   streaming_off_bps)
                          if streaming_off_bps else 0.0)

    default_geom = (B == 128 and T == 16)
    # stage keys in the canonical telemetry order (telemetry.INGEST_STAGES
    # is the one vocabulary shared by bench rows, the HANDYRL_TPU_TIMING
    # epoch line, and the exported stage_seconds histograms)
    snap = timer.snapshot()
    stages = {s: snap[s] for s in telemetry.INGEST_STAGES if s in snap}
    stages.update({s: snap[s] for s in snap if s not in stages})
    emit(new_bps, (new_bps / ref_bps) if ref_bps else 0.0,
         backend=probe.get('backend', 'unknown'),
         device=probe.get('device_kind', 'unknown'),
         batch_size=B, forward_steps=T, episodes=n_eps,
         timed_batches=n_batches,
         reference_batches_per_sec=round(ref_bps, 2),
         vs_baseline_def=('arena builder / reference builder, identical '
                          'Batcher machinery'),
         stages=stages, run_id=telemetry.run_id(),
         tracing_on_batches_per_sec=round(traced_bps, 2),
         tracing_overhead_pct=round(
             100.0 * (1.0 - traced_bps / new_bps), 2) if new_bps else 0.0,
         trace_sample_rate=trace_rate,
         recorder_on_batches_per_sec=round(recorder_on_bps, 2),
         recorder_off_batches_per_sec=round(recorder_off_bps, 2),
         recorder_overhead_pct=round(recorder_overhead, 2),
         perf_plane_on_batches_per_sec=round(perf_plane_on_bps, 2),
         perf_plane_off_batches_per_sec=round(perf_plane_off_bps, 2),
         perf_plane_overhead_pct=round(perf_plane_overhead, 2),
         spool_on_batches_per_sec=round(spool_on_bps, 2),
         spool_off_batches_per_sec=round(spool_off_bps, 2),
         spool_overhead_pct=round(spool_overhead, 2),
         streaming_on_batches_per_sec=round(streaming_on_bps, 2),
         streaming_off_batches_per_sec=round(streaming_off_bps, 2),
         chunk_overhead_pct=round(chunk_overhead, 2),
         geometry=('headline' if default_geom else 'dryrun'))


def _actor_env() -> str:
    return os.environ.get('BENCH_ACTOR_ENV', 'HungryGeese')


def _actor_args(backend: str, workers: int):
    """Merged train_args for one bench fleet (the gather subtree's view).

    ``backend`` is the per-host actor backend: 'worker' (per-worker B=1
    reference), 'engine' (host batched InferenceEngine), or 'device' (the
    fused on-device rollout fleet — DeviceActorGather)."""
    from handyrl_tpu.config import apply_defaults
    args = apply_defaults({'env_args': {'env': _actor_env()}})['train_args']
    args['env'] = {'env': _actor_env()}
    args['seed'] = 11
    args['eval_rate'] = 0.0
    args['worker'] = {'num_parallel': workers, 'num_gathers': 1,
                      'base_worker_id': 0, 'backend': backend}
    args['inference'] = dict(args['inference'],
                             enabled=(backend == 'engine'),
                             batch_wait_ms=float(os.environ.get(
                                 'BENCH_ACTOR_WAIT_MS', '2')))
    if backend == 'device':
        args['generation'] = dict(
            args.get('generation') or {}, backend='device',
            device_actor_envs=int(os.environ.get(
                'BENCH_ACTOR_DEVICE_ENVS', '16')),
            device_actor_chunk_steps=int(os.environ.get(
                'BENCH_ACTOR_DEVICE_CHUNK', '16')))
    return args


def _actor_fleet_run(backend: str, workers: int, total: int, warm: int,
                     snapshot: dict, players: list) -> dict:
    """Spawn ONE real gather (+ its worker processes) over a pipe and act as
    its learner: serve 'g' tasks (each stamped with a deterministic
    sample_key), the fixed model snapshot, and collect episode uploads.

    Returns episodes/sec past the warmup, the packed episode payloads (for
    byte-comparison across inference paths), and the gather's final
    telemetry beacon (engine batch-fill counters ride it)."""
    import time as _time
    from handyrl_tpu.connection import (HEARTBEAT_KIND, pack,
                                        spawn_pipe_workers)
    from handyrl_tpu.worker import gather_loop

    args = _actor_args(backend, workers)
    ep = spawn_pipe_workers(1, gather_loop,
                            lambda i, c: (args, c, i))[0]
    served = 0
    episodes, arrivals, failed = [], [], 0
    beacon = {}
    while True:
        try:
            kind, body = ep.recv()
        except (EOFError, OSError):
            break
        if kind == HEARTBEAT_KIND:
            beacon = body or {}
            continue
        if kind == 'args':
            out = []
            for _ in body:
                if served < total:
                    out.append({'role': 'g', 'player': list(players),
                                'model_id': {p: 1 for p in players},
                                'sample_key': served})
                    served += 1
                else:
                    out.append(None)
            ep.send(out)
        elif kind == 'model':
            ep.send(snapshot)
        elif kind == 'episode':
            now = _time.time()
            for e in body:
                if e is None:
                    failed += 1
                    continue
                episodes.append(e)
                arrivals.append(now)
            ep.send(None)
        elif kind == 'result':
            ep.send(None)
    measured = max(0, len(episodes) - warm)
    span = (arrivals[-1] - arrivals[warm - 1]) if measured > 0 else 0.0
    steps = sum(e['steps'] for e in episodes[warm:])
    tele = (beacon.get('telemetry') or {}).get('counters') or {}
    return {
        'episodes_per_sec': measured / span if span > 0 else 0.0,
        'requests_per_sec': steps / span if span > 0 else 0.0,
        'records': sorted(pack(e) for e in episodes),
        'failed': failed,
        'engine_requests': tele.get('engine_requests_total', 0),
        'engine_batches': tele.get('engine_batches_total', 0),
        'stamped': sum(1 for e in episodes if e.get('record_version')),
        'device_plies': tele.get('device_actor_plies_total', 0),
        'device_episodes': tele.get('device_actor_episodes_total', 0),
        'device_divergence': tele.get('device_actor_divergence_total', 0),
    }


def run_actor(probe: dict):
    """BENCH_MODE=actor: the fleet actor data path, CPU-measurable.

    Env knobs (CI smoke shrinks them): BENCH_ACTOR_WORKERS (default 4),
    BENCH_ACTOR_EPISODES (timed episodes, default 96), BENCH_ACTOR_WARMUP
    (default 16), BENCH_ACTOR_WAIT_MS (engine batch_wait_ms, default 2),
    BENCH_ACTOR_ENV (default TicTacToe).
    """
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu import telemetry
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.model import ModelWrapper

    workers = int(os.environ.get('BENCH_ACTOR_WORKERS', '6'))
    warm = int(os.environ.get('BENCH_ACTOR_WARMUP', '4'))
    total = warm + int(os.environ.get('BENCH_ACTOR_EPISODES', '12'))

    # ONE fixed model snapshot (seeded params) served to both fleets: the
    # record comparison needs both paths acting for the same policy
    env = make_env({'env': _actor_env()})
    env.reset()
    wrapper = ModelWrapper(env.net(), seed=7)
    wrapper.ensure_params(env.observation(env.players()[0]))
    snapshot = wrapper.snapshot()
    players = env.players()

    import contextlib
    backend_row = os.environ.get('BENCH_ACTOR_BACKEND', '').strip().lower()
    if backend_row == 'device':
        # device-backend row: the fused on-device rollout fleet against
        # the engine fleet — same harness, seeds, and task stream. Strict
        # envs (TicTacToe/ConnectX) byte-compare; device-contract envs
        # carry a record_version stamp instead (never silently divergent).
        # The device gather uploads a whole task block per burst, so a
        # steady-state rate needs >= 2 blocks in the timed window with the
        # full first block (compile + warmup) excluded — arrival spans
        # inside one burst only measure upload serialization.
        lanes = int(os.environ.get('BENCH_ACTOR_DEVICE_ENVS', '16'))
        warm = max(warm, lanes)
        total = warm + max(total - warm, 2 * lanes)
        with contextlib.redirect_stdout(sys.stderr):
            base = _actor_fleet_run('engine', workers, total, warm,
                                    snapshot, players)
            dev = _actor_fleet_run('device', workers, total, warm,
                                   snapshot, players)
        emit(dev['episodes_per_sec'],
             (dev['episodes_per_sec'] / base['episodes_per_sec'])
             if base['episodes_per_sec'] else 0.0,
             metric=('fleet episodes/sec (%s, device actor backend: fused '
                     'on-device rollout scan vs the engine-batched host '
                     'fleet)' % _actor_env()),
             backend=probe.get('backend', 'unknown'),
             device=probe.get('device_kind', 'unknown'),
             workers=workers, episodes=total - warm, warmup=warm,
             engine_episodes_per_sec=round(base['episodes_per_sec'], 2),
             requests_per_sec=round(dev['requests_per_sec'], 2),
             device_actor_envs=int(os.environ.get(
                 'BENCH_ACTOR_DEVICE_ENVS', '16')),
             device_plies=dev['device_plies'],
             device_divergence=dev['device_divergence'],
             records_identical=(dev['records'] == base['records']
                                and len(dev['records']) == total),
             records_stamped=dev['stamped'],
             failed_episodes=base['failed'] + dev['failed'],
             vs_baseline_def=('device-backend episodes/sec / engine '
                              'episodes/sec, identical harness, seeds '
                              'and task stream'),
             env=_actor_env(),
             run_id=telemetry.run_id(),
             geometry=('headline'
                       if (total - warm >= 12
                           and _actor_env() == 'HungryGeese')
                       else 'dryrun'))
        return
    with contextlib.redirect_stdout(sys.stderr):
        # child-process startup prints must not break the one-line contract
        base = _actor_fleet_run('worker', workers, total, warm, snapshot,
                                players)
        eng = _actor_fleet_run('engine', workers, total, warm, snapshot,
                               players)

    fill = eng['engine_requests'] / max(1, eng['engine_batches'])
    emit(eng['episodes_per_sec'],
         (eng['episodes_per_sec'] / base['episodes_per_sec'])
         if base['episodes_per_sec'] else 0.0,
         backend=probe.get('backend', 'unknown'),
         device=probe.get('device_kind', 'unknown'),
         workers=workers, episodes=total - warm, warmup=warm,
         per_worker_episodes_per_sec=round(base['episodes_per_sec'], 2),
         requests_per_sec=round(eng['requests_per_sec'], 2),
         per_worker_requests_per_sec=round(base['requests_per_sec'], 2),
         batch_fill=round(fill, 2),
         records_identical=(eng['records'] == base['records']
                            and len(eng['records']) == total),
         failed_episodes=base['failed'] + eng['failed'],
         vs_baseline_def=('engine episodes/sec / per-worker B=1 '
                          'episodes/sec, identical harness, seeds and '
                          'task stream'),
         env=_actor_env(),
         run_id=telemetry.run_id(),
         geometry=('headline'
                   if (workers >= 4 and total - warm >= 12
                       and _actor_env() == 'HungryGeese')
                   else 'dryrun'))


def _mesh_child():
    """BENCH_MODE=mesh subprocess: measure ONE mesh size.

    The virtual-device count (XLA_FLAGS) must be fixed before jax imports,
    hence a process per row. Prints exactly one JSON dict on stdout:
    wall steps/sec of the sharded program, the per-shard strong-scaling
    projection (single-device rate at batch B/ndev), the first-step loss
    from fixed seeds (cross-mesh parity), and the per-device staged batch
    bytes counted by ``mesh_shard_bytes_total``.
    """
    import jax
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    handyrl_tpu.setup_compile_cache()
    import jax.numpy as jnp
    import numpy as np
    from handyrl_tpu import telemetry
    from handyrl_tpu.ops.train_step import build_update_step
    from handyrl_tpu.parallel import partition
    from handyrl_tpu.parallel.mesh import make_mesh, shard_batch

    ndev = int(os.environ['BENCH_MESH_CHILD'])
    B = int(os.environ.get('BENCH_MESH_BATCH', '128'))
    T = int(os.environ.get('BENCH_MESH_T', '16'))
    steps = int(os.environ.get('BENCH_MESH_STEPS', '5'))
    devices = jax.devices()
    if len(devices) < ndev:
        print(json.dumps({'ndev': ndev,
                          'error': 'only %d device(s)' % len(devices)}))
        return
    lr = jnp.asarray(1e-5, jnp.float32)
    module, cfg, batch, state = headline_setup(B, T, seed=0)
    row = {'ndev': ndev, 'batch': B, 'forward_steps': T,
           'timed_steps': steps}

    shard_bytes = telemetry.REGISTRY.counter('mesh_shard_bytes_total')
    mark = shard_bytes.value
    if ndev > 1:
        mesh = make_mesh(devices[:ndev])
        state_sh = partition.tree_shardings(mesh, state,
                                            partition.DEFAULT_RULES)
        step = build_update_step(module, cfg, mesh=mesh, donate=False,
                                 state_shardings=state_sh)
        batch = shard_batch(mesh, batch)   # per-shard host->device staging
        row['shard_bytes_per_device'] = (shard_bytes.value - mark) // ndev
    else:
        step = build_update_step(module, cfg, donate=False)
        batch = jax.tree_util.tree_map(jnp.asarray, batch)
        row['shard_bytes_per_device'] = sum(
            np.asarray(v).nbytes
            for v in jax.tree_util.tree_leaves(batch))

    # first-step loss from identical seeds: the cross-mesh parity probe
    _, metrics = step(state, batch, lr)
    row['loss'] = float(np.asarray(metrics['total']))
    sec, flops, _bytes = time_compiled_step(step, state, batch, lr, steps)
    row['wall_steps_per_sec'] = round(1.0 / sec, 4)
    row['flops_per_step'] = flops

    # per-shard strong-scaling projection: each device of a real ndev-mesh
    # runs the B/ndev program; its measured single-device rate is the
    # global step rate collectives aside (on a virtual one-core mesh the
    # wall clock above time-slices all ndev shards, so it cannot show this)
    if ndev > 1 and B % ndev == 0:
        m2, c2, b2, s2 = headline_setup(B // ndev, T, seed=0)
        step2 = build_update_step(m2, c2, donate=False)
        b2 = jax.tree_util.tree_map(jnp.asarray, b2)
        sec2, _f, _b = time_compiled_step(step2, s2, b2, lr, steps)
        row['projected_steps_per_sec'] = round(1.0 / sec2, 4)
    else:
        row['projected_steps_per_sec'] = row['wall_steps_per_sec']
    print(json.dumps(row), flush=True)


_FORCE_DEV_RE = r'--xla_force_host_platform_device_count=\d+'


def run_mesh(probe: dict):
    """BENCH_MODE=mesh: SGD-throughput scaling of the sharded learner.

    Env knobs (CI smoke shrinks them): BENCH_MESH_DEVICES ('1,2,4,8'),
    BENCH_MESH_BATCH (global batch, default 128), BENCH_MESH_T (forward
    steps, default 16), BENCH_MESH_STEPS (timed steps per row, default 5).
    On the CPU backend each mesh size runs on XLA host-device partitioning
    (a virtual mesh); real accelerators use the first ndev devices.
    """
    import re

    cpu = probe.get('backend') == 'cpu'
    ndevs = [int(x) for x in os.environ.get(
        'BENCH_MESH_DEVICES', '1,2,4,8').split(',') if x.strip()]
    rows = []
    for ndev in ndevs:
        if not cpu and int(probe.get('n', 1)) < ndev:
            continue   # not enough physical devices; no virtualizing a TPU
        env = dict(os.environ, BENCH_MESH_CHILD=str(ndev))
        if cpu:
            flags = re.sub(_FORCE_DEV_RE, '', env.get('XLA_FLAGS', ''))
            env['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=%d'
                % ndev).strip()
            env['JAX_PLATFORMS'] = 'cpu'
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        _CHILDREN.append(proc)
        out, _ = proc.communicate()
        try:
            row = json.loads(out.strip().splitlines()[-1])
        except (ValueError, IndexError):
            row = {'ndev': ndev, 'error': 'child rc=%s' % proc.returncode}
        rows.append(row)

    good = [r for r in rows if 'error' not in r]
    if not good:
        emit(error='no mesh size produced a measurement',
             rows=rows, device=probe.get('device_kind', 'unknown'))
        return
    base = min(good, key=lambda r: r['ndev'])
    # scaling: wall clock where the mesh is real hardware, the per-shard
    # projection where it is host-virtualized (one core serializes shards)
    key = 'wall_steps_per_sec' if not cpu else 'projected_steps_per_sec'
    for r in good:
        r['scaling_vs_1dev'] = round(r[key] / base['wall_steps_per_sec'], 3)
        r['loss_rel_err'] = (abs(r['loss'] - base['loss'])
                             / max(abs(base['loss']), 1e-12))
    peak = good[-1]
    at4 = next((r for r in good if r['ndev'] == 4), peak)
    emit(peak[key], at4['scaling_vs_1dev'],
         backend=probe.get('backend', 'unknown'),
         device=probe.get('device_kind', 'unknown'),
         batch=base.get('batch'), forward_steps=base.get('forward_steps'),
         devices_measured=[r['ndev'] for r in good],
         rows=rows,
         virtual_mesh=cpu,
         scaling_at_max=peak['scaling_vs_1dev'],
         max_loss_rel_err=max(r['loss_rel_err'] for r in good),
         vs_baseline_def=('steps/sec scaling at 4 devices vs the 1-device '
                          'step at the same global batch; %s'
                          % ('per-shard strong-scaling projection (B/ndev '
                             'single-device rate) on the host-virtualized '
                             'mesh — the wall column time-slices every '
                             'shard onto this host\'s cores' if cpu
                             else 'measured wall clock')),
         geometry=('headline' if base.get('batch') == 128
                   and base.get('forward_steps') == 16 else 'dryrun'))


def _serve_client_load(host, port, model, obs, legal, n_clients, warmup,
                       requests, base_seed, client_factory=None):
    """Drive ``n_clients`` concurrent ServiceClients (one thread each) at
    the service: per-client warmup then ``requests`` timed sequential round
    trips. Returns (requests/sec over the timed span, latency list,
    error count). ``client_factory(ci)`` swaps the client class (the fleet
    phase routes through RoutedClient against a resolver port)."""
    import threading
    from handyrl_tpu.generation import sample_seed
    from handyrl_tpu.serving.client import ServiceClient

    latencies, errors = [], [0]
    spans = []
    lock = threading.Lock()
    barrier = threading.Barrier(n_clients)

    def run(ci):
        if client_factory is not None:
            client = client_factory(ci)
        else:
            client = ServiceClient(host, port, timeout=60.0, name='c%d' % ci)
        mine = []
        try:
            for k in range(warmup):
                client.request(model, obs, legal=legal,
                               seed=sample_seed(base_seed, (ci, k), 0))
            barrier.wait(timeout=120)
            t_start = time.monotonic()
            for k in range(requests):
                t0 = time.monotonic()
                client.request(model, obs, legal=legal,
                               seed=sample_seed(base_seed,
                                                (ci, warmup + k), 0))
                mine.append(time.monotonic() - t0)
            t_end = time.monotonic()
            with lock:
                latencies.extend(mine)
                spans.append((t_start, t_end))
        except Exception:
            with lock:
                errors[0] += 1
        finally:
            client.close()

    threads = [threading.Thread(target=run, args=(ci,),
                                name='serve-bench-%d' % ci)
               for ci in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if not spans:
        return 0.0, [], errors[0]
    span = max(e for _s, e in spans) - min(s for s, _e in spans)
    return len(latencies) / max(span, 1e-9), latencies, errors[0]


def _serve_fleet_phase(env_name, wrapper, obs, legal, n_clients, requests,
                       warmup, wait_ms, single_rps):
    """The BENCH_MODE=serve fleet phase: a resolver + BENCH_SERVE_REPLICAS
    managed replicas under the same client load, routed through
    RoutedClient. Returns the extra emit keys (fleet_* scaling vs the
    single-service row, rolling-promote p99 before/during, resolver drain
    exit code), or {} when BENCH_SERVE_REPLICAS=0 disables the phase."""
    import contextlib
    import shutil
    import signal as _signal
    import tempfile
    import threading
    import numpy as np
    from handyrl_tpu.serving.fleet import RoutedClient
    from handyrl_tpu.serving.registry import ModelRegistry

    replicas = int(os.environ.get('BENCH_SERVE_REPLICAS', '2'))
    if replicas <= 0:
        return {}
    root = tempfile.mkdtemp(prefix='bench_fleet_registry.')
    proc = None
    try:
        with contextlib.redirect_stdout(sys.stderr):
            reg = ModelRegistry(root)
            reg.publish('bench', snapshot=wrapper.snapshot(), version=1,
                        steps=1, promote=True)
            # the rolling-promote candidate: published, not yet champion
            reg.publish('bench', snapshot=wrapper.snapshot(), version=2,
                        steps=2, promote=False)
        proc = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving', '--fleet',
             '--env', env_name, '--registry', root, '--port', '0',
             '--line', 'bench', '--replicas', str(replicas),
             '--heartbeat', '0.5', '--wait-ms', str(wait_ms),
             '--max-clients', str(n_clients + 8)],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        _CHILDREN.append(proc)
        ready = json.loads(proc.stdout.readline())['fleet_ready']
        port = int(ready['port'])
        model = 'bench@champion'

        def routed(ci):
            return RoutedClient('localhost', port, timeout=60.0,
                                name='f%d' % ci)

        fleet_rps, lat_before, err_f = _serve_client_load(
            'localhost', port, model, obs, legal, n_clients, warmup,
            requests, base_seed=41, client_factory=routed)

        # rolling promote under load: every replica warms bench@2 before
        # the champion flips, so the client-side p99 must not blip
        admin = RoutedClient('localhost', port, timeout=60.0, name='padm')
        promote_result = {}

        def do_promote():
            try:
                promote_result.update(admin.promote('bench@2', timeout=120))
            except Exception as exc:  # noqa: BLE001 — reported in the row
                promote_result['error'] = str(exc)[:200]

        pt = threading.Thread(target=do_promote, name='bench-promote')
        pt.start()
        _rps_during, lat_during, err_p = _serve_client_load(
            'localhost', port, model, obs, legal, n_clients, 0,
            requests, base_seed=43, client_factory=routed)
        pt.join(timeout=120)
        admin.close()

        # resolver SIGTERM: drains managed replicas, exits 75
        proc.send_signal(_signal.SIGTERM)
        try:
            fleet_exit = proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.terminate()
            fleet_exit = None

        def p99(lat):
            ms = [1e3 * v for v in lat]
            return round(float(np.percentile(ms, 99)), 2) if ms else 0.0

        # replication scaling needs cores >= replicas: on a starved host
        # the replicas time-slice one core and fleet_vs_single measures
        # routing overhead, not the scaling headline — stamp the cores so
        # the row is interpretable either way
        cores = os.cpu_count() or 1
        return {
            'fleet_replicas': replicas,
            'fleet_host_cores': cores,
            'fleet_requests_per_sec': round(fleet_rps, 2),
            'fleet_vs_single': (round(fleet_rps / single_rps, 2)
                                if single_rps else 0.0),
            'fleet_client_errors': err_f + err_p,
            'promote_p99_before_ms': p99(lat_before),
            'promote_p99_during_ms': p99(lat_during),
            'promote_warmed': promote_result.get('warmed', []),
            'promote_error': promote_result.get('error'),
            'fleet_drain_exit_code': fleet_exit,
        }
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        shutil.rmtree(root, ignore_errors=True)


def run_serve(probe: dict):
    """BENCH_MODE=serve: the standalone serving tier, CPU-measurable.

    Env knobs (CI smoke shrinks them): BENCH_SERVE_CLIENTS (default 8),
    BENCH_SERVE_REQUESTS (timed requests per client, default 40),
    BENCH_SERVE_WARMUP (per client, default 4), BENCH_SERVE_ENV (default
    HungryGeese), BENCH_SERVE_WAIT_MS (engine batch_wait_ms, default 2),
    BENCH_SERVE_DRAIN (in-flight requests per client through the SIGTERM,
    default 3), BENCH_SERVE_REPLICAS (fleet-phase managed replicas,
    default 2, 0 skips the fleet phase).
    """
    import contextlib
    import shutil
    import signal as _signal
    import tempfile
    import numpy as np
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.generation import sample_seed
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.serving.client import ServiceClient
    from handyrl_tpu.serving.registry import ModelRegistry

    env_name = os.environ.get('BENCH_SERVE_ENV', 'HungryGeese')
    n_clients = int(os.environ.get('BENCH_SERVE_CLIENTS', '8'))
    requests = int(os.environ.get('BENCH_SERVE_REQUESTS', '40'))
    warmup = int(os.environ.get('BENCH_SERVE_WARMUP', '4'))
    wait_ms = os.environ.get('BENCH_SERVE_WAIT_MS', '2')
    drain_n = int(os.environ.get('BENCH_SERVE_DRAIN', '3'))
    engine_backend = os.environ.get(
        'BENCH_SERVE_ENGINE_BACKEND', 'cpu').strip().lower() or 'cpu'

    env = make_env({'env': env_name})
    env.reset()
    obs = env.observation(env.players()[0])
    legal = env.legal_actions(env.players()[0])
    wrapper = ModelWrapper(env.net(), seed=7)
    wrapper.ensure_params(obs)

    root = tempfile.mkdtemp(prefix='bench_serve_registry.')
    try:
        with contextlib.redirect_stdout(sys.stderr):
            ModelRegistry(root).publish('bench', snapshot=wrapper.snapshot(),
                                        version=1, steps=1, promote=True)
        proc = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving',
             '--env', env_name, '--registry', root, '--port', '0',
             '--line', 'bench', '--wait-ms', str(wait_ms),
             '--engine-backend', engine_backend,
             '--max-clients', str(n_clients + 4)],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        _CHILDREN.append(proc)
        ready = json.loads(proc.stdout.readline())['serving_ready']
        port = int(ready['port'])
        model = 'bench@champion'

        # single-client reference first: the vs_baseline denominator (what
        # one sequential client extracts from the same service)
        base_rps, _lat1, err1 = _serve_client_load(
            'localhost', port, model, obs, legal, 1, warmup,
            max(8, requests // 2), base_seed=29)
        many_rps, latencies, err_n = _serve_client_load(
            'localhost', port, model, obs, legal, n_clients, warmup,
            requests, base_seed=31)

        status_client = ServiceClient('localhost', port, name='status')
        status = status_client.status(timeout=30)
        fill = (status.get('engine_requests', 0)
                / max(1, status.get('engine_batches', 1)))

        # tracing-off vs tracing-on(rate 0.1) adjacent A/B pair (the PR 7
        # ingest-pair shape): the 'trace' admin op flips the SAME warmed
        # service process between legs, alternating best-of-3 per side —
        # the serving-path span cost is below one-shot run-to-run noise
        from handyrl_tpu import telemetry as _tel
        trace_rate = float(os.environ.get('BENCH_TRACE_RATE', '0.1'))
        trace_dir_t = tempfile.mkdtemp(prefix='bench_serve_trace.')
        tr_rounds = []
        try:
            for i in range(3):
                status_client.call_admin({'op': 'trace', 'dir': trace_dir_t,
                                          'rate': trace_rate}, timeout=30)
                _tel.configure_tracing(trace_dir_t, trace_rate, force=True)
                on_rps, _lt, _et = _serve_client_load(
                    'localhost', port, model, obs, legal, n_clients, 0,
                    requests, base_seed=51 + i)
                status_client.call_admin({'op': 'trace', 'dir': '',
                                          'rate': None}, timeout=30)
                _tel.configure_tracing('', None, force=True)
                off_rps, _lt, _et = _serve_client_load(
                    'localhost', port, model, obs, legal, n_clients, 0,
                    requests, base_seed=61 + i)
                tr_rounds.append((on_rps, off_rps))
        finally:
            try:
                status_client.call_admin({'op': 'trace', 'dir': '',
                                          'rate': None}, timeout=30)
            except Exception:   # noqa: BLE001 — best-effort reset
                pass
            _tel.configure_tracing('', None, force=True)
            shutil.rmtree(trace_dir_t, ignore_errors=True)
        tracing_on_rps = max(on for on, _ in tr_rounds)
        tracing_off_rps = max(off for _, off in tr_rounds)
        tracing_overhead = (100.0 * (1.0 - tracing_on_rps / tracing_off_rps)
                            if tracing_off_rps else 0.0)

        # measured graceful drain: every in-flight request through the
        # SIGTERM must be ANSWERED (ok or an explicit drain error), and the
        # service must exit 75 (the PreemptionGuard supervisor contract)
        rids = [status_client.submit(model, obs, legal=legal,
                                     seed=sample_seed(37, (0, k), 0))
                for k in range(drain_n * n_clients)]
        t_term = time.monotonic()
        proc.send_signal(_signal.SIGTERM)
        drained = unanswered = 0
        for rid in rids:
            try:
                status_client.collect(rid, timeout=30)
                drained += 1
            except TimeoutError:
                unanswered += 1
            except Exception:
                drained += 1          # an error reply is still an answer
        try:
            exit_code = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.terminate()
            exit_code = None
        drain_seconds = time.monotonic() - t_term
        status_client.close()

        # fleet phase: resolver + replicas under the same load, routed —
        # fleet_vs_single is the replication scaling headline
        fleet_keys = _serve_fleet_phase(
            env_name, wrapper, obs, legal, n_clients, requests, warmup,
            wait_ms, many_rps)

        lat_ms = sorted(1e3 * v for v in latencies)
        pct = (lambda q: round(float(np.percentile(lat_ms, q)), 2)) \
            if lat_ms else (lambda q: 0.0)
        emit(many_rps, (many_rps / base_rps) if base_rps else 0.0,
             backend=probe.get('backend', 'unknown'),
             device=probe.get('device_kind', 'unknown'),
             env=env_name, clients=n_clients,
             engine_backend=engine_backend,
             requests_per_client=requests,
             requests_measured=len(lat_ms),
             single_client_requests_per_sec=round(base_rps, 2),
             p50_ms=pct(50), p95_ms=pct(95), p99_ms=pct(99),
             batch_fill=round(fill, 2),
             shed_total=int(status.get('shed', 0)),
             client_errors=err1 + err_n,
             drain_requests=len(rids), drain_answered=drained,
             drain_unanswered=unanswered,
             drain_seconds=round(drain_seconds, 2),
             drain_exit_code=exit_code,
             tracing_on_requests_per_sec=round(tracing_on_rps, 2),
             tracing_off_requests_per_sec=round(tracing_off_rps, 2),
             tracing_overhead_pct=round(tracing_overhead, 2),
             trace_sample_rate=trace_rate,
             **fleet_keys,
             vs_baseline_def=('%d-client req/s over single-client req/s '
                              'against the same service — the continuous-'
                              'batching concurrency gain' % n_clients),
             geometry=('headline'
                       if (n_clients >= 8 and requests >= 32
                           and env_name == 'HungryGeese') else 'dryrun'))
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_gateway(probe: dict):
    """BENCH_MODE=gateway: the match-gateway session tier, CPU-measurable.

    Env knobs (CI smoke shrinks them): BENCH_GATEWAY_SESSIONS (concurrent
    sessions, default 8), BENCH_GATEWAY_MATCHES (matches per session,
    default 2), BENCH_GATEWAY_ENV (default TicTacToe — short matches, so
    the rate measures the session machinery, not the game), and
    BENCH_GATEWAY_REPLICAS (default 2). BENCH_GATEWAY_KILL=0 disables the
    mid-run replica SIGKILL (on by default: the row's dropped_sessions=0
    under the kill IS the robustness headline).
    """
    import contextlib
    import random
    import shutil
    import signal as _signal
    import tempfile
    import threading
    import numpy as np
    import handyrl_tpu
    handyrl_tpu.honor_platform_env()
    from handyrl_tpu.environment import make_env
    from handyrl_tpu.model import ModelWrapper
    from handyrl_tpu.serving.fleet import RoutedClient
    from handyrl_tpu.serving.gateway import GatewayClient
    from handyrl_tpu.serving.registry import ModelRegistry

    env_name = os.environ.get('BENCH_GATEWAY_ENV', 'TicTacToe')
    n_sessions = int(os.environ.get('BENCH_GATEWAY_SESSIONS', '8'))
    matches = int(os.environ.get('BENCH_GATEWAY_MATCHES', '2'))
    replicas = int(os.environ.get('BENCH_GATEWAY_REPLICAS', '2'))
    kill = os.environ.get('BENCH_GATEWAY_KILL', '1') != '0'

    env = make_env({'env': env_name})
    env.reset()
    obs = env.observation(env.players()[0])
    wrapper = ModelWrapper(env.net(), seed=7)
    wrapper.ensure_params(obs)

    root = tempfile.mkdtemp(prefix='bench_gateway_registry.')
    fleet_proc = gw_proc = rc = None
    try:
        with contextlib.redirect_stdout(sys.stderr):
            ModelRegistry(root).publish('bench', snapshot=wrapper.snapshot(),
                                        version=1, steps=1, promote=True)
        fleet_proc = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving', '--fleet',
             '--env', env_name, '--registry', root, '--port', '0',
             '--line', 'bench', '--replicas', str(replicas),
             '--heartbeat', '0.3'],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        _CHILDREN.append(fleet_proc)
        fleet_port = int(json.loads(
            fleet_proc.stdout.readline())['fleet_ready']['port'])
        gw_proc = subprocess.Popen(
            [sys.executable, '-m', 'handyrl_tpu.serving', '--gateway',
             '--resolver', 'localhost:%d' % fleet_port,
             '--registry', root, '--env', env_name,
             '--gateway-model', 'bench@champion',
             '--gateway-workers', str(min(8, n_sessions)),
             '--max-sessions', str(n_sessions + 4), '--seed', '11'],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        _CHILDREN.append(gw_proc)
        gport = int(json.loads(
            gw_proc.stdout.readline())['gateway_ready']['port'])

        ply_lat = []
        lat_lock = threading.Lock()
        errors = [0]

        def play_matches(ci, n, collect=True):
            rng = random.Random(1000 + ci)
            done = 0
            cl = GatewayClient('localhost', gport, timeout=60.0,
                               name='b%d' % ci)
            try:
                for _ in range(n):
                    r = cl.open(env_name, seat=0)
                    sid = r['sid']
                    while not r.get('done'):
                        action = (rng.choice(r['legal'])
                                  if r.get('to_move') and r.get('legal')
                                  else None)
                        t0 = time.monotonic()
                        r = cl.play(sid, action)
                        if collect:
                            with lat_lock:
                                ply_lat.append(time.monotonic() - t0)
                    done += 1
            except Exception:   # noqa: BLE001 — reported in the row
                errors[0] += 1
            finally:
                cl.close()
            return done

        # one warmup match first (replica engines compile on first touch),
        # then the single-session reference: the vs_baseline denominator
        play_matches(0, 1, collect=False)
        t0 = time.monotonic()
        base_done = play_matches(0, max(2, matches), collect=False)
        base_rate = base_done / max(time.monotonic() - t0, 1e-9)

        # N concurrent sessions, a replica SIGKILLed mid-run: every match
        # must still complete (stranded sessions rebuilt by journal replay)
        rc = RoutedClient('localhost', fleet_port, timeout=30.0)
        table = {r['replica']: r for r in rc.replicas()}
        victim = sorted(table)[0] if (kill and len(table) > 1) else None
        completed = [0] * n_sessions
        threads = [threading.Thread(
            target=lambda ci=ci: completed.__setitem__(
                ci, play_matches(ci, matches)),
            name='bench-gw-%d' % ci) for ci in range(n_sessions)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        if victim is not None:
            time.sleep(0.5)
            try:
                os.kill(int(table[victim]['pid']), _signal.SIGKILL)
            except (OSError, KeyError, TypeError):
                victim = None
        for t in threads:
            t.join(timeout=300)
        many_rate = sum(completed) / max(time.monotonic() - t0, 1e-9)

        status_cl = GatewayClient('localhost', gport, timeout=30.0,
                                  name='bstatus')
        status = status_cl.status()

        # tracing-off vs tracing-on(rate 0.1) adjacent A/B pair (the PR 7
        # ingest-pair shape): the 'trace' admin op flips the SAME warmed
        # gateway + every replica between legs, alternating best-of-3 per
        # side on the sequential single-session match rate
        from handyrl_tpu import telemetry as _tel
        from handyrl_tpu.serving.client import (ServiceClient,
                                                parse_endpoint)
        trace_rate = float(os.environ.get('BENCH_TRACE_RATE', '0.1'))
        trace_dir_t = tempfile.mkdtemp(prefix='bench_gateway_trace.')

        def toggle_tracing(dirpath, rate):
            status_cl._call({'op': 'trace', 'dir': dirpath, 'rate': rate})
            for row in rc.replicas():
                try:
                    host, rport = parse_endpoint(row['endpoint'])
                    sc = ServiceClient(host, rport, timeout=30.0,
                                       name='btrace', dial_retries=1)
                    try:
                        sc.call_admin({'op': 'trace', 'dir': dirpath,
                                       'rate': rate}, timeout=30)
                    finally:
                        sc.close()
                except Exception:  # noqa: BLE001 — a corpse mid-respawn
                    pass
            _tel.configure_tracing(dirpath, rate, force=True)

        tr_rounds = []
        # a TicTacToe match is ~10-20ms here, so a 2-match leg is pure
        # scheduler noise — each measured leg needs enough matches that
        # the rate estimate is dominated by ply work, not jitter
        ab_matches = max(10, matches)
        ab_rounds = int(os.environ.get('BENCH_TRACE_ROUNDS', '5'))
        try:
            # one unmeasured leg first — the replica respawned after the
            # SIGKILL recompiles its engine on first touch, and that cost
            # must not land in either side of the pair — then alternate
            # which side goes first per round so settling drift cancels
            play_matches(99, ab_matches, collect=False)
            for i in range(ab_rounds):
                legs = {}
                order = ('on', 'off') if i % 2 == 0 else ('off', 'on')
                for leg in order:
                    if leg == 'on':
                        toggle_tracing(trace_dir_t, trace_rate)
                    else:
                        toggle_tracing('', None)
                    t1 = time.monotonic()
                    d = play_matches((100 if leg == 'on' else 200) + i,
                                     ab_matches, collect=False)
                    legs[leg] = d / max(time.monotonic() - t1, 1e-9)
                tr_rounds.append((legs['on'], legs['off']))
        finally:
            try:
                toggle_tracing('', None)
            except Exception:   # noqa: BLE001 — best-effort reset
                pass
            shutil.rmtree(trace_dir_t, ignore_errors=True)
        tracing_on_rate = max(on for on, _ in tr_rounds)
        tracing_off_rate = max(off for _, off in tr_rounds)
        tracing_overhead = (100.0 * (1.0 - tracing_on_rate
                                     / tracing_off_rate)
                            if tracing_off_rate else 0.0)
        status_cl.close()

        # gateway SIGTERM drains to exit 75 (the supervisor contract),
        # then the fleet follows
        gw_proc.send_signal(_signal.SIGTERM)
        try:
            gw_exit = gw_proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            gw_proc.terminate()
            gw_exit = None
        fleet_proc.send_signal(_signal.SIGTERM)
        try:
            fleet_exit = fleet_proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            fleet_proc.terminate()
            fleet_exit = None

        lat_ms = sorted(1e3 * v for v in ply_lat)
        pct = (lambda q: round(float(np.percentile(lat_ms, q)), 2)) \
            if lat_ms else (lambda q: 0.0)
        emit(many_rate, (many_rate / base_rate) if base_rate else 0.0,
             backend=probe.get('backend', 'unknown'),
             device=probe.get('device_kind', 'unknown'),
             env=env_name, sessions=n_sessions,
             matches_per_session=matches,
             matches_completed=sum(completed),
             fleet_replicas=replicas,
             host_cores=os.cpu_count() or 1,
             single_session_matches_per_sec=round(base_rate, 2),
             ply_p50_ms=pct(50), ply_p95_ms=pct(95), ply_p99_ms=pct(99),
             plies_measured=len(lat_ms),
             killed_replica=victim,
             dropped_sessions=int(status.get('dropped', 0)),
             reconstructs=int(status.get('reconstructs', 0)),
             replayed_plies=int(status.get('replayed_plies', 0)),
             reconstruct_mismatches=int(status.get('mismatches', 0)),
             handoffs=int(status.get('handoffs', 0)),
             shed_total=int(status.get('shed', 0)),
             outcomes_recorded=int(status.get('outcomes', 0)),
             client_errors=errors[0],
             tracing_on_matches_per_sec=round(tracing_on_rate, 2),
             tracing_off_matches_per_sec=round(tracing_off_rate, 2),
             tracing_overhead_pct=round(tracing_overhead, 2),
             trace_sample_rate=trace_rate,
             gateway_drain_exit_code=gw_exit,
             fleet_drain_exit_code=fleet_exit,
             vs_baseline_def=('%d-session matches/s over single-session '
                              'matches/s against the same gateway — the '
                              'session concurrency gain' % n_sessions),
             geometry=('headline'
                       if (n_sessions >= 8 and matches >= 2
                           and env_name == 'TicTacToe') else 'dryrun'))
    finally:
        if rc is not None:
            rc.close()
        for proc in (gw_proc, fleet_proc):
            if proc is not None and proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)


def _last_measured() -> str:
    """The newest on-silicon bench-headline row, summarized for the
    backend-unavailable JSON line — so a wedged tunnel at the driver's
    round-end run still points at the concrete measured number."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'benchmarks.jsonl')
    try:
        best = None
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if (row.get('row') == 'bench-headline'
                        and row.get('backend') == 'tpu'):
                    best = row
        if best is None:
            return 'none recorded'
        return '%.1f traj/s (%.1fx baseline) on %s at %s' % (
            best.get('value', 0.0), best.get('vs_baseline', 0.0),
            best.get('device', '?'), best.get('time', '?'))
    except OSError:
        return 'none recorded'


def main():
    if os.environ.get('BENCH_MESH_CHILD'):
        # mesh-mode measurement subprocess: one JSON row, no probe/alarm
        # machinery (the parent owns the deadline and emit contract)
        _mesh_child()
        return
    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    deadline = float(os.environ.get('BENCH_DEADLINE_SEC', '600'))
    signal.signal(signal.SIGALRM, _shutdown)
    signal.alarm(int(deadline))

    probe = probe_backend(min(120.0, deadline / 3))
    if 'error' in probe:
        last = _last_measured()
        emit(error='backend unavailable: ' + probe['error'],
             note='last measured TPU value for this metric: '
                  '%s (benchmarks.jsonl bench-headline rows)' % (last,))
        return
    try:
        if _active_mode() == 'ingest':
            run_ingest(probe)
        elif _active_mode() == 'actor':
            run_actor(probe)
        elif _active_mode() == 'mesh':
            run_mesh(probe)
        elif _active_mode() == 'serve':
            run_serve(probe)
        elif _active_mode() == 'gateway':
            run_gateway(probe)
        else:
            run_bench(probe)
    except Exception as exc:  # noqa: BLE001 — the contract is: always emit
        emit(error='%s: %s' % (type(exc).__name__, str(exc)[:200]),
             device=probe.get('device_kind', 'unknown'))


if __name__ == '__main__':
    main()
