"""Headline benchmark: learner trajectories/sec on the flagship config.

Measures the full compiled update step (forward + targets + losses + grads +
Adam) on GeeseNet at the reference's default batch geometry (batch 128 x
forward_steps 16, config.yaml:12-18), on the default JAX device (the TPU
chip under the driver). ``vs_baseline`` is measured-ours / measured-reference:
the denominator comes from bench_baseline.json, produced by
scripts/baseline_torch_learner.py — the same step in PyTorch on this host's
CPU (the reference publishes no numbers of its own; see BASELINE.md).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import os
import time

import numpy as np


def _wait_for_backend(retries: int = 6, delay: float = 20.0):
    """The axon TPU tunnel can be transiently unavailable (exclusive
    single-client grant); retry init with backoff before giving up."""
    import jax
    for attempt in range(retries):
        try:
            return jax.devices()
        except RuntimeError as e:
            if attempt == retries - 1:
                raise
            print('# backend unavailable (%s); retry %d/%d in %.0fs'
                  % (str(e).splitlines()[0][:80], attempt + 1, retries, delay),
                  flush=True)
            time.sleep(delay)


def main():
    import jax
    import jax.numpy as jnp
    _wait_for_backend()
    from handyrl_tpu.models import build
    from handyrl_tpu.ops.losses import LossConfig
    from handyrl_tpu.ops.train_step import build_update_step, init_train_state
    from handyrl_tpu.parallel.mesh import make_mesh, shard_batch
    from __graft_entry__ import _synthetic_batch

    B, T = 128, 16
    steps = 30

    module = build('GeeseNet')
    rng = np.random.RandomState(0)
    batch = _synthetic_batch(B, T, 1, (17, 7, 11), 4, rng)
    params = module.init(jax.random.PRNGKey(0), batch['observation'][:, 0, 0], None)
    state = init_train_state(params)

    cfg = LossConfig(turn_based_training=False, observation=True,
                     policy_target='TD', value_target='TD', gamma=0.99)
    devices = jax.devices()
    mesh = make_mesh(devices) if len(devices) > 1 else None
    step = build_update_step(module, cfg, mesh=mesh, donate=False)
    if mesh is not None:
        batch = shard_batch(mesh, batch)
    lr = jnp.asarray(1e-5, jnp.float32)

    # warmup/compile
    for _ in range(3):
        state, metrics = step(state, batch, lr)
    jax.block_until_ready(metrics['total'])

    t0 = time.time()
    for _ in range(steps):
        state, metrics = step(state, batch, lr)
    jax.block_until_ready(metrics['total'])
    dt = time.time() - t0
    traj_per_sec = B * steps / dt

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'bench_baseline.json')
    vs_baseline = 0.0
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
        ref = base.get('torch_cpu_trajectories_per_sec', 0.0)
        if ref > 0:
            vs_baseline = traj_per_sec / ref

    print(json.dumps({
        'metric': 'learner trajectories/sec (GeeseNet B=128 T=16, full update step)',
        'value': round(traj_per_sec, 2),
        'unit': 'trajectories/sec',
        'vs_baseline': round(vs_baseline, 2),
    }))


if __name__ == '__main__':
    main()
